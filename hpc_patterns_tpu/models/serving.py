"""Continuous batching: a serving loop over the ragged paged cache.

The round-4 machinery (per-sequence positions, per-row pool writes,
page-table indirection — models/decode.py) provided the building
blocks; this module is the loop that makes them a serving system, the
vLLM-style capacity story:

- a **page free-list**: the pool is a shared arena; each admitted
  sequence takes exactly the pages its prompt + budget needs and
  returns them on completion;
- **admission**: new sequences enter as soon as pages free up —
  batch slots don't wait for the whole batch to finish (the static-
  batching waste: every row pays the longest row's wall clock);
- **per-row completion**: on-device ``pos``/``limit`` cursors let every
  row advance at its own length; budget exhaustion and (optional) EOS
  end a row independently of its neighbors.

TPU shape of the loop: the inner stepper is ONE jit containing a
``lax.scan`` over ``chunk`` tokens (iteration-level scheduling
quantized to ``chunk``) — host work and dispatch latency amortize over
the chunk, exactly the reference's amortize-the-submit-path discipline
(SURVEY.md §3.1's repetition loop). Finished rows stop advancing
INSIDE the chunk (their ``pos`` freezes at ``limit``; the frozen write
re-targets the row's own last slot, which the row still owns), so a
chunk never writes past a row's allocation. Idle slots point their
table row at a dedicated TRASH page and their writes land there —
garbage in, never read, discarded.

Correctness contract (oracle-tested): every admitted sequence's
emitted tokens are exactly ``paged_generate``'s for the same prompt
and budget, regardless of what was scheduled around it.

Reference lineage: the benchmark-IS-the-test discipline
(aurora.mpich.miniapps/src/CMakeLists.txt:39-50) — the engine's
throughput benchmark (benchmarks/bench_serving.py) validates the
oracle on every run.
"""

from __future__ import annotations

import time
from dataclasses import dataclass, field
from functools import partial

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from hpc_patterns_tpu.harness import metrics as metricslib
from hpc_patterns_tpu.models.decode import (
    init_paged_cache,
    paged_decode_step,
    paged_prefill,
)
from hpc_patterns_tpu.models.transformer import TransformerConfig


@dataclass
class Request:
    """One sequence to serve: ``prompt`` (T,) int32, up to ``max_new``
    generated tokens (fewer if ``eos_id`` fires). ``t_submit`` stamps
    queue entry so admission can attribute time-to-first-token."""
    prompt: np.ndarray
    max_new: int
    seq_id: int = -1
    t_submit: float = 0.0


@dataclass
class _Slot:
    seq_id: int = -1
    pages: list = field(default_factory=list)
    prompt_len: int = 0
    out: list = field(default_factory=list)
    active: bool = False
    t_admit: float = 0.0


@partial(jax.jit, static_argnames=("cfg", "chunk", "eos_id", "mesh"),
         donate_argnums=(1, 2, 3, 4))
def _chunk_step(params, cache, pos, limit, tokens, *, cfg, chunk,
                eos_id, mesh):
    """``chunk`` ragged decode steps in one trace: rows advance while
    ``pos < limit``; an emitted ``eos_id`` pulls the row's limit down
    to its current end. Emits the picked token per step (valid where
    the step was active). eos_id < 0 disables EOS. Module-level jit
    (static config) so every engine instance with the same config
    shares one compilation."""

    def step(carry, _):
        cache, pos, limit, tok = carry
        active = pos < limit
        logits, cache = paged_decode_step(params, cache, pos, tok, cfg,
                                          mesh=mesh)
        nxt = jnp.argmax(logits, axis=-1).astype(jnp.int32)
        nxt = jnp.where(active, nxt, tok)
        if eos_id >= 0:
            limit = jnp.where(active & (nxt == eos_id),
                              jnp.minimum(limit, pos + 1), limit)
        pos = jnp.where(active, pos + 1, pos)
        return (cache, pos, limit, nxt), nxt

    (cache, pos, limit, tokens), out = lax.scan(
        step, (cache, pos, limit, tokens), None, length=chunk
    )
    return cache, pos, limit, tokens, out


@partial(jax.jit,
         static_argnames=("cfg", "dcfg", "gamma", "rounds", "eos_id",
                          "mesh"),
         donate_argnums=(2, 3, 4, 5, 6))
def _spec_chunk(params, dparams, cache, dcache, pos, limit, cur, *,
                cfg, dcfg, gamma, rounds, eos_id, mesh=None):
    """``rounds`` draft-assisted serving rounds in ONE dispatch
    (greedy): each round is THE shared speculative round body
    (models/speculative.paged_round — one acceptance/emit definition
    for the engine and speculative_generate_batched) at each row's own
    cursor, advancing 1..gamma+1 tokens per round. Budget and EOS
    truncation happen ON DEVICE between rounds (``adv`` clamps at the
    row's limit; an emitted eos pulls the limit to the row's end), so
    the host pays one round trip per ``rounds`` — the draft-mode
    counterpart of _chunk_step's dispatch amortization. Rows at their
    limit run at a clamped cursor (garbage lands in pages they own or
    the trash page). Returns (cache, dcache, pos, limit, cur, emits,
    advs): per-round tokens (rounds, B, gamma+1) and valid counts
    (rounds, B) for the host to append."""
    from hpc_patterns_tpu.models.speculative import paged_round

    B = pos.shape[0]
    rows = jnp.arange(B)
    # the engine serves greedily (greedy=True below): paged_round never
    # reads the key or temperature on that path — these are inert
    # placeholders filling its sampling signature, NOT live sampling
    inert_greedy_key = jax.random.PRNGKey(0)
    inert_temperature = jnp.float32(1.0)

    def one_round(carry, _):
        cache, dcache, pos, limit, cur = carry
        active = pos < limit
        pos_eff = jnp.where(active, pos, 0)
        cache, dcache, a, emit, _ = paged_round(
            params, cfg, dparams, dcfg, cache, dcache, pos_eff, cur,
            gamma, inert_greedy_key, True, 0, inert_temperature,
            mesh=mesh)
        adv = jnp.where(active,
                        jnp.minimum(a + 1, limit - pos), 0)
        if eos_id >= 0:
            k = jnp.arange(gamma + 1)[None, :]
            is_eos = (emit == eos_id) & (k < adv[:, None])
            has = jnp.any(is_eos, axis=1)
            first = jnp.argmax(is_eos, axis=1)
            adv = jnp.where(has, first + 1, adv)
        new_cur = emit[rows, jnp.clip(adv - 1, 0, gamma)]
        cur = jnp.where(adv > 0, new_cur, cur)
        pos = pos + adv
        if eos_id >= 0:
            limit = jnp.where(has, pos, limit)
        return (cache, dcache, pos, limit, cur), (emit, adv)

    (cache, dcache, pos, limit, cur), (emits, advs) = lax.scan(
        one_round, (cache, dcache, pos, limit, cur), None,
        length=rounds)
    return cache, dcache, pos, limit, cur, emits, advs


@partial(jax.jit, static_argnames=("cfg", "page_size", "mesh"),
         donate_argnums=(2,))
def _prefill_one(params, prompt, cache_one, *, cfg, page_size, mesh):
    """One-row prefill through the shared pool (jitted; compiles per
    distinct prompt length — bucket/pad prompts upstream if compile
    count matters). ``cache_one`` is donated: the pool IS the capacity
    lever, so admissions must not double it."""
    return paged_prefill(params, prompt, cfg, cache_one, page_size,
                         mesh=mesh)


class ContinuousBatcher:
    """Serve a stream of :class:`Request`s through ``slots`` concurrent
    rows of one paged pool.

    ``pool_pages``: the shared arena size (pages; one extra trash page
    is appended internally). ``pages_per_seq``: table width = the max
    pages any single sequence may hold (size requests with
    :meth:`pages_needed`). ``chunk``: decode steps per jitted dispatch
    — admission/eviction happen at chunk boundaries (larger amortizes
    host+dispatch; 1 = immediate). Greedy decoding (the serving
    oracle); ``eos_id`` optionally ends rows early. ``mesh``:
    tp-sharded serving — pools/kernel shard exactly like
    ``paged_generate(..., mesh=...)``.

    ``draft_params``/``draft_cfg``/``gamma``: draft-assisted serving —
    speculative ROUNDS (draft proposes gamma, target verifies in one
    ragged extend; rows advance 1..gamma+1 tokens at their own
    acceptance). ``chunk`` here means ROUNDS per jitted dispatch
    (budget/EOS truncation runs on device between rounds), so
    admission/eviction happen every chunk·(1..gamma+1) tokens.
    Composes with ``mesh``: draft steps ride the shard_map
    paged-kernel route, the ragged extend partitions via GSPMD (tp
    must divide BOTH models' kv_heads).
    """

    def __init__(self, params, cfg: TransformerConfig, *, slots: int,
                 pool_pages: int, pages_per_seq: int, page_size: int,
                 chunk: int = 8, eos_id: int | None = None, mesh=None,
                 draft_params=None, draft_cfg: TransformerConfig | None
                 = None, gamma: int = 4, emit=None):
        if cfg.n_experts:
            # paged serving is dense-model territory so far
            raise ValueError("continuous batching: dense models only")
        if draft_params is not None:
            if draft_cfg is None:
                raise ValueError("draft_params needs draft_cfg")
            if draft_cfg.vocab != cfg.vocab:
                raise ValueError("draft/target vocab mismatch")
            if gamma < 1:
                raise ValueError(f"gamma must be >= 1, got {gamma}")
        self.draft_params = draft_params
        self.draft_cfg = draft_cfg
        self.gamma = gamma
        # speculative rounds touch positions up to pos+gamma; the page
        # allocation (NOT max_seq) must cover the overshoot
        self.spec_slack = gamma + 1 if draft_params is not None else 0
        self.params = params
        self.cfg = cfg
        self.slots = slots
        self.page_size = page_size
        self.pages_per_seq = pages_per_seq
        self.chunk = chunk
        self.eos_id = -1 if eos_id is None else int(eos_id)
        self.mesh = mesh
        self.trash = pool_pages  # the appended trash page's id
        table = np.full((slots, pages_per_seq), self.trash, np.int32)
        self.cache = init_paged_cache(
            cfg, slots, pages_per_seq, page_size,
            pool_pages=pool_pages + 1, table=jnp.asarray(table),
        )
        if draft_params is not None:
            # the draft pool mirrors the target's page geometry and
            # SHARES the page table (one allocation decision serves
            # both caches)
            self.dcache = init_paged_cache(
                draft_cfg, slots, pages_per_seq, page_size,
                pool_pages=pool_pages + 1, table=jnp.asarray(table),
            )
        self.free_pages = list(range(pool_pages))
        self._table = table  # host mirror
        self.pos = jnp.zeros((slots,), jnp.int32)
        self.limit = jnp.zeros((slots,), jnp.int32)
        self.tokens = jnp.zeros((slots,), jnp.int32)
        self._slots = [_Slot() for _ in range(slots)]
        self._queue: list[Request] = []
        self.finished: dict[int, np.ndarray] = {}
        self._next_id = 0
        # observability hook (the framework's metrics/logging
        # subsystem, SURVEY.md §5): a callable taking keyword fields —
        # pass harness.RunLog.emit for JSONL records of admissions,
        # completions, and queue waits; None = silent
        self._emit = emit or (lambda **kw: None)

    # -- admission ---------------------------------------------------------

    @staticmethod
    def pages_needed(prompt_len: int, max_new: int, page_size: int, *,
                     gamma: int | None = None) -> int:
        """Pages one request holds in this engine: prompt + budget,
        plus the speculative overshoot slack (gamma+1) when a draft
        serves — THE sizing rule; callers building their own pools
        (serve_app) must use it rather than re-deriving the slack."""
        slack = (gamma + 1) if gamma is not None else 0
        return -(-(prompt_len + max_new + slack) // page_size)

    def _pages_for(self, prompt_len: int, max_new: int) -> int:
        return self.pages_needed(
            prompt_len, max_new, self.page_size,
            gamma=self.gamma if self.draft_params is not None else None)

    def submit(self, prompt, max_new: int, seq_id: int | None = None) -> int:
        """Enqueue a sequence; returns its id. Tokens appear in
        ``finished[id]`` once served."""
        prompt = np.asarray(prompt, np.int32)
        if prompt.ndim != 1 or prompt.size == 0:
            raise ValueError(f"prompt must be 1-D nonempty, {prompt.shape}")
        if max_new < 1:
            raise ValueError(f"max_new must be >= 1, got {max_new}")
        need = self._pages_for(prompt.size, max_new)
        if need > self.pages_per_seq:
            raise ValueError(
                f"prompt {prompt.size} + budget {max_new} (+ spec "
                f"slack {self.spec_slack}) needs {need} pages > "
                f"pages_per_seq {self.pages_per_seq}"
            )
        if prompt.size + max_new > self.cfg.max_seq:
            raise ValueError(
                f"prompt {prompt.size} + budget {max_new} exceeds "
                f"max_seq {self.cfg.max_seq}"
            )
        sid = self._next_id if seq_id is None else seq_id
        if (sid in self.finished
                or any(r.seq_id == sid for r in self._queue)
                or any(s.active and s.seq_id == sid
                       for s in self._slots)):
            raise ValueError(
                f"seq_id {sid} already queued/active/finished — outputs "
                "would silently merge under one key"
            )
        self._next_id = max(self._next_id, sid) + 1
        self._queue.append(Request(prompt, max_new, sid,
                                   t_submit=time.perf_counter()))
        metricslib.get_metrics().gauge("serve.queue_depth").set(
            len(self._queue))
        return sid

    def _try_admit(self) -> bool:
        """Admit the longest-waiting request that fits a free slot and
        the free page list. FCFS with skip: a large request at the head
        does not block a small one behind it (documented head-of-line
        tradeoff; flip to strict FCFS by breaking instead of
        continuing)."""
        free_slot = next(
            (i for i, s in enumerate(self._slots) if not s.active), None)
        if free_slot is None:
            return False
        for qi, req in enumerate(self._queue):
            need = self._pages_for(req.prompt.size, req.max_new)
            if need <= len(self.free_pages):
                self._queue.pop(qi)
                self._admit(free_slot, req, need)
                return True
        return False

    def _admit(self, slot: int, req: Request, need: int):
        pages = [self.free_pages.pop() for _ in range(need)]
        row = np.full((self.pages_per_seq,), self.trash, np.int32)
        row[:need] = pages
        self._table[slot] = row
        self.cache["table"] = jnp.asarray(self._table)
        T = int(req.prompt.size)
        # one-row prefill THROUGH the shared pool: the scatter touches
        # only this row's pages (compiles per distinct prompt length —
        # bucket/pad prompts upstream if that matters)
        one = dict(self.cache)
        # fresh upload from the host mirror, NOT a slice of the device
        # table: a full-range slice can alias the same buffer, and
        # _prefill_one donates its table — an alias would delete the
        # engine's live table with it
        one["table"] = jnp.asarray(self._table[slot:slot + 1])
        with metricslib.span("serve.prefill", prompt_len=T):
            logits, out = _prefill_one(
                self.params, jnp.asarray(req.prompt)[None, :], one,
                cfg=self.cfg, page_size=self.page_size, mesh=self.mesh,
            )
        for k, v in out.items():
            if k != "table":
                self.cache[k] = v
        if self.draft_params is not None:
            self.dcache["table"] = jnp.asarray(self._table)
            done = dict(self.dcache)
            done["table"] = jnp.asarray(self._table[slot:slot + 1])
            _, dout = _prefill_one(
                self.draft_params, jnp.asarray(req.prompt)[None, :],
                done, cfg=self.draft_cfg, page_size=self.page_size,
                mesh=self.mesh,
            )
            for k, v in dout.items():
                if k != "table":
                    self.dcache[k] = v
        first = int(jnp.argmax(logits[0]))
        st = self._slots[slot]
        st.seq_id, st.pages, st.prompt_len = req.seq_id, pages, T
        st.out, st.active = [first], True
        st.t_admit = time.perf_counter()
        self._emit(kind="serve_admit", seq_id=req.seq_id, slot=slot,
                   pages=need, prompt_len=T, budget=req.max_new,
                   free_pages=len(self.free_pages),
                   queued=len(self._queue))
        m = metricslib.get_metrics()
        if m.enabled:
            # prefill emitted the first token: admit time IS first-token
            # time for this engine (TTFT counted from submit)
            m.histogram("serve.ttft_s").observe(
                st.t_admit - (req.t_submit or st.t_admit))
            m.gauge("serve.queue_depth").set(len(self._queue))
            m.gauge("serve.free_pages").set(len(self.free_pages))
            m.counter("serve.admitted").inc()
        self.pos = self.pos.at[slot].set(T)
        done = (self.eos_id >= 0 and first == self.eos_id) or req.max_new == 1
        self.limit = self.limit.at[slot].set(
            T if done else T + req.max_new - 1)
        self.tokens = self.tokens.at[slot].set(first)
        if done:
            self._finish(slot)

    # -- completion --------------------------------------------------------

    def _finish(self, slot: int):
        st = self._slots[slot]
        self.finished[st.seq_id] = np.asarray(st.out, np.int32)
        self._emit(kind="serve_finish", seq_id=st.seq_id, slot=slot,
                   tokens=len(st.out), pages_freed=len(st.pages))
        m = metricslib.get_metrics()
        if m.enabled:
            dt = time.perf_counter() - st.t_admit
            m.histogram("serve.per_token_s").observe(
                dt / max(1, len(st.out)))
            m.counter("serve.finished").inc()
            m.counter("serve.tokens").inc(len(st.out))
            m.gauge("serve.free_pages").set(
                len(self.free_pages) + len(st.pages))
        self.free_pages.extend(st.pages)
        self._table[slot] = self.trash
        self.cache["table"] = jnp.asarray(self._table)
        if self.draft_params is not None:
            self.dcache["table"] = jnp.asarray(self._table)
        self._slots[slot] = _Slot()
        self.pos = self.pos.at[slot].set(0)
        self.limit = self.limit.at[slot].set(0)

    # -- the loop ----------------------------------------------------------

    def _run_chunk(self):
        pos_start = np.asarray(self.pos)
        with metricslib.span("serve.decode_round", chunk=self.chunk):
            self.cache, self.pos, self.limit, self.tokens, out = _chunk_step(
                self.params, self.cache, self.pos, self.limit, self.tokens,
                cfg=self.cfg, chunk=self.chunk, eos_id=self.eos_id,
                mesh=self.mesh,
            )
            out = np.asarray(out)  # (chunk, slots); readback closes the span
        limit_new = np.asarray(self.limit)
        for i, st in enumerate(self._slots):
            if not st.active:
                continue
            valid = int(np.clip(limit_new[i] - pos_start[i], 0,
                                self.chunk))
            st.out.extend(int(t) for t in out[:valid, i])
            if pos_start[i] + valid >= limit_new[i]:
                self._finish(i)

    def _run_spec_round(self):
        """``chunk`` draft-assisted rounds per dispatch: budget/EOS
        truncation happens on device between rounds (_spec_chunk), so
        over-acceptance beyond a limit is discarded there and the
        caches' stale rows get overwritten when the cursor re-crosses
        them (the speculative invariant). The host just appends each
        round's valid tokens and finishes exhausted rows."""
        with metricslib.span("serve.spec_round", rounds=self.chunk,
                             gamma=self.gamma):
            (self.cache, self.dcache, self.pos, self.limit, self.tokens,
             emits, advs) = _spec_chunk(
                self.params, self.draft_params, self.cache, self.dcache,
                self.pos, self.limit, self.tokens,
                cfg=self.cfg, dcfg=self.draft_cfg, gamma=self.gamma,
                rounds=self.chunk, eos_id=self.eos_id, mesh=self.mesh,
            )
            emits = np.asarray(emits)  # (rounds, slots, gamma+1)
            advs = np.asarray(advs)    # (rounds, slots)
        pos_np = np.asarray(self.pos)
        limit_np = np.asarray(self.limit)
        for i, st in enumerate(self._slots):
            if not st.active:
                continue
            for k in range(advs.shape[0]):
                v = int(advs[k, i])
                if v:
                    st.out.extend(int(t) for t in emits[k, i, :v])
            if pos_np[i] >= limit_np[i]:
                self._finish(i)

    def run(self):
        """Serve until queue and slots drain. Returns ``finished``:
        {seq_id: np.ndarray of emitted tokens (<= max_new; ends at
        eos_id when enabled)}."""
        while self._queue or any(s.active for s in self._slots):
            while self._try_admit():
                pass
            if not any(s.active for s in self._slots):
                if self._queue:
                    raise RuntimeError(
                        "serving deadlock: waiting requests but no "
                        "admissible slot/pages (pool too small for the "
                        "smallest waiting request)"
                    )
                break
            if self.draft_params is not None:
                self._run_spec_round()
            else:
                self._run_chunk()
        return self.finished
