"""Known-clean: jitted functions return values; the CALLER stores them
(the engine pattern: ``self.pos, ... = _chunk_step(...)``)."""

from functools import partial

import jax


@partial(jax.jit, donate_argnums=(1,))
def _step(params, state):
    return state * params


class Engine:
    def advance(self):
        # assignment to self happens OUTSIDE the trace
        self.state = _step(self.params, self.state)


def not_jitted(engine, x):
    # plain python: storing on self is fine outside a trace
    engine.last = x
    return x
