"""Tensor parallelism: column/row sharded matmuls over a mesh axis.

Megatron-style TP expressed with the comm layer: the row-parallel
reduction IS the reference's allreduce — selectable between the library
collective (``psum``, ≙ MPI_Allreduce, allreduce-mpi-sycl.cpp:61-67) and
the hand-built ring (≙ :173-182), keeping the ring-vs-collective
comparison axis (§2.3(b)) available one level up the stack.

All functions are rank-local (inside ``shard_map``); the TPU win is that
XLA overlaps the trailing collective with the next layer's compute when
shardings are expressed this way (the latency-hiding the reference's
concurrency suite measures at the queue level).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from hpc_patterns_tpu.comm import collectives, ring


def column_parallel(x, w_local, b_local=None):
    """Y_local = x @ W_local: weights column-sharded on the TP axis,
    activations replicated in, feature-sharded out. No communication —
    the all-gather is deferred to the paired row-parallel matmul."""
    y = jnp.dot(x, w_local)
    if b_local is not None:
        y = y + b_local
    return y


def row_parallel(x_local, w_local, b=None, *, axis: str, algorithm: str = "collective"):
    """Y = sum_ranks(x_local @ W_local): weights row-sharded, inputs
    feature-sharded, output replicated via allreduce.

    ``algorithm``: ``"collective"`` (lax.psum) or ``"ring"`` (the
    hand-built ppermute ring) — the miniapp's ``-a`` switch
    (allreduce-mpi-sycl.cpp:122-124) for tensor parallelism.
    """
    partial = jnp.dot(x_local, w_local)
    if algorithm == "collective":
        y = collectives.allreduce(partial, axis, "sum")
    elif algorithm == "ring":
        y = ring.ring_allreduce(partial, axis)
    else:
        raise ValueError(f"unknown algorithm {algorithm!r}")
    if b is not None:
        y = y + b
    return y


def row_parallel_scatter(x_local, w_local, *, axis: str, scatter_axis: int = -1):
    """Row-parallel matmul ending in reduce-scatter instead of allreduce
    (the sequence-parallel-Megatron fusion): output stays sharded on
    ``scatter_axis``, halving wire bytes vs allreduce."""
    partial = jnp.dot(x_local, w_local)
    ndim = partial.ndim
    return collectives.reduce_scatter(
        partial, axis, scatter_axis=scatter_axis % ndim
    )


def tp_mlp(x, w_in_local, w_out_local, *, axis: str, activation=None,
           algorithm: str = "collective"):
    """The canonical TP block: column-parallel in-projection, elementwise
    activation on the shard, row-parallel out-projection — exactly one
    allreduce per block."""
    h = column_parallel(x, w_in_local)
    h = (activation or jax.nn.gelu)(h)
    return row_parallel(h, w_out_local, axis=axis, algorithm=algorithm)
