"""SLO accounting: TTFT/TPOT attainment per priority class, goodput.

Raw tokens/s is the throughput number every serving benchmark reports,
and it is the wrong number under load: a server can post excellent
tok/s while every interactive request blows its latency target (the
classic throughput-vs-SLO tension). The production metric is
**goodput** — tokens/s counted ONLY over requests that met their
declared service-level objectives — reported *next to* raw tok/s so
the gap between them is the visible cost of a scheduling policy.

Two latency objectives per class (the standard LLM-serving pair):

- **TTFT** (time to first token): submit → first token available.
- **TPOT** (time per output token): the mean inter-token time over the
  rest of the generation, ``(t_finish - t_first) / (tokens - 1)``.

A request ATTAINS its SLO iff it was served (not shed) and both
targets hold (a ``None`` target is trivially attained). Shed requests
— dropped by admission control before serving — count against
attainment but contribute zero tokens.

Attainment says WHETHER a class met its targets; it does not say
which mechanism ate the time when it did not. ``harness/budget.py``
splits the same two targets into per-segment allowances (shares of
TTFT/TPOT a lifecycle segment may consume) and emits a breach record
per segment that overspends — the budget layer on top of the verdict
this module renders.

The input is the serving engine's per-request stats table
(``ContinuousBatcher.stats``: ``t_submit``/``t_first``/``t_finish``/
``tokens``/``priority``/``outcome``/``preemptions`` per request).
Percentiles here are EXACT (numpy over the raw per-request values, not
bucketed) — the request count is benchmark-scale, and SLO verdicts
should not be quantized; the metrics-registry histograms
(``serve.ttft_s`` etc.) remain the bucketed live view.

Import-light (numpy only), same discipline as loadgen/chaos.
"""

from __future__ import annotations

from collections import deque
from dataclasses import dataclass
from typing import Any, Iterable, Mapping

import numpy as np

PERCENTILES = (50.0, 95.0, 99.0)


@dataclass(frozen=True)
class SLOTarget:
    """Declared targets for one priority class; None = no target on
    that axis (trivially attained)."""
    ttft_s: float | None = None
    tpot_s: float | None = None


def targets_from_classes(classes: Iterable) -> dict[int, SLOTarget]:
    """{priority: SLOTarget} from ``loadgen.PriorityClass``-shaped
    objects (duck-typed: ``priority``/``ttft_slo_s``/``tpot_slo_s``)."""
    return {int(c.priority): SLOTarget(ttft_s=c.ttft_slo_s,
                                       tpot_s=c.tpot_slo_s)
            for c in classes}


def _pcts(values: list[float]) -> dict[str, float | None]:
    if not values:
        return {f"p{int(q)}": None for q in PERCENTILES}
    arr = np.asarray(values, np.float64)
    return {f"p{int(q)}": float(np.percentile(arr, q))
            for q in PERCENTILES}


def request_latencies(rec: Mapping[str, Any]) -> tuple[float | None,
                                                       float | None]:
    """(ttft_s, tpot_s) of one served request's stats record; None
    where undefined (unserved / single-token generations have no
    TPOT)."""
    if rec.get("t_first") is None or rec.get("t_submit") is None:
        return None, None
    ttft = float(rec["t_first"]) - float(rec["t_submit"])
    tokens = int(rec.get("tokens") or 0)
    tpot = None
    if rec.get("t_finish") is not None and tokens > 1:
        tpot = (float(rec["t_finish"]) - float(rec["t_first"])) / (
            tokens - 1)
    return ttft, tpot


def attained(rec: Mapping[str, Any], target: SLOTarget) -> bool:
    """Did this request meet its class targets? Shed requests never
    attain; missing targets are trivially met."""
    if rec.get("outcome") != "ok":
        return False
    ttft, tpot = request_latencies(rec)
    if target.ttft_s is not None and (ttft is None or ttft > target.ttft_s):
        return False
    if target.tpot_s is not None and tpot is not None \
            and tpot > target.tpot_s:
        return False
    return True


class AttainmentWindow:
    """A sliding window over the most recent per-request SLO judgments
    — the ONE attainment signal the serving planes emit per round (as a
    metrics gauge, a trace counter, and a ``kind=plane_attainment``
    RunLog record), so the in-process autoscaler, the launched router,
    and the offline autofit threshold fitter all read the same number
    instead of three subtly different recomputations.

    Judgments enter as requests RESOLVE (served → :func:`attained`
    verdict; shed → not attained), so the window tracks recent service
    quality, not the full-run average :func:`attainment` reports at the
    end. Pure bookkeeping: no clocks, no I/O."""

    def __init__(self, window: int = 64):
        if window < 1:
            raise ValueError(f"window must be >= 1, got {window}")
        self.window = int(window)
        self._judgments: deque[tuple[int, bool]] = deque(
            maxlen=self.window)
        self.judged = 0      # lifetime totals (per-round deltas are
        self.attained = 0    # the autoscaler's Signals currency)

    def judge(self, rec: Mapping[str, Any], target: SLOTarget) -> bool:
        """Judge one resolved stats record against its class target and
        fold the verdict into the window."""
        ok = attained(rec, target)
        self.observe(int(rec.get("priority", 0)), ok)
        return ok

    def observe(self, priority: int, ok: bool) -> None:
        self._judgments.append((int(priority), bool(ok)))
        self.judged += 1
        self.attained += int(bool(ok))

    def snapshot(self) -> dict[str, Any]:
        """``{"n", "overall", "per_class"}`` over the current window;
        ``overall`` is None while nothing has been judged."""
        per: dict[int, list[bool]] = {}
        for prio, ok in self._judgments:
            per.setdefault(prio, []).append(ok)
        n = len(self._judgments)
        return {
            "n": n,
            "overall": (sum(ok for _, ok in self._judgments) / n
                        if n else None),
            "per_class": {p: sum(v) / len(v)
                          for p, v in sorted(per.items())},
        }


def attainment(stats: Mapping[int, Mapping[str, Any]],
               targets: Mapping[int, SLOTarget],
               wall_s: float) -> dict[str, Any]:
    """The SLO rollup over an engine's stats table.

    Returns ``{"wall_s", "classes": {priority: {...}}, "total": {...}}``
    where each class entry carries counts (``n``/``served``/``shed``/
    ``attained``), exact TTFT/TPOT percentiles, raw ``tok_s`` and
    ``goodput_tok_s`` (SLO-attained tokens over the same wall clock),
    and the declared targets; ``total`` aggregates across classes. A
    priority with no declared target gets the all-None
    :class:`SLOTarget` (trivially attained when served)."""
    classes: dict[int, dict[str, Any]] = {}
    by_prio: dict[int, list[Mapping[str, Any]]] = {}
    for rec in stats.values():
        by_prio.setdefault(int(rec.get("priority", 0)), []).append(rec)
    tot_tokens = tot_good = 0
    tot_n = tot_served = tot_shed = tot_attained = tot_preempt = 0
    for prio in sorted(by_prio):
        recs = by_prio[prio]
        target = targets.get(prio, SLOTarget())
        ttfts, tpots = [], []
        n_served = n_shed = n_att = tokens = good = n_preempt = 0
        for rec in recs:
            if rec.get("outcome") == "shed":
                n_shed += 1
                continue
            if rec.get("outcome") != "ok":
                continue  # still in flight: not judged
            n_served += 1
            tokens += int(rec.get("tokens") or 0)
            n_preempt += int(rec.get("preemptions") or 0)
            ttft, tpot = request_latencies(rec)
            if ttft is not None:
                ttfts.append(ttft)
            if tpot is not None:
                tpots.append(tpot)
            if attained(rec, target):
                n_att += 1
                good += int(rec.get("tokens") or 0)
        n = n_served + n_shed
        classes[prio] = {
            "n": n, "served": n_served, "shed": n_shed,
            "attained": n_att, "preemptions": n_preempt,
            "tokens": tokens,
            "attained_frac": (n_att / n) if n else None,
            "ttft_s": _pcts(ttfts), "tpot_s": _pcts(tpots),
            "tok_s": tokens / wall_s if wall_s > 0 else 0.0,
            "goodput_tok_s": good / wall_s if wall_s > 0 else 0.0,
            "target": {"ttft_s": target.ttft_s, "tpot_s": target.tpot_s},
        }
        tot_tokens += tokens
        tot_good += good
        tot_n += n
        tot_served += n_served
        tot_shed += n_shed
        tot_attained += n_att
        tot_preempt += n_preempt
    return {
        "wall_s": wall_s,
        "classes": classes,
        "total": {
            "n": tot_n, "served": tot_served, "shed": tot_shed,
            "attained": tot_attained, "preemptions": tot_preempt,
            "tokens": tot_tokens,
            "attained_frac": (tot_attained / tot_n) if tot_n else None,
            "tok_s": tot_tokens / wall_s if wall_s > 0 else 0.0,
            "goodput_tok_s": tot_good / wall_s if wall_s > 0 else 0.0,
        },
    }


def format_slo(report: Mapping[str, Any]) -> str:
    """The human table: one row per class plus the total — goodput
    NEXT TO raw tok/s, the whole point."""
    lines = []
    t = report["total"]
    lines.append(
        f"SLO over {t['n']} request(s) in {report['wall_s']:.3f}s: "
        f"{t['attained']} attained / {t['shed']} shed / "
        f"{t['preemptions']} preemption(s); "
        f"{t['tok_s']:,.1f} tok/s raw, "
        f"{t['goodput_tok_s']:,.1f} tok/s goodput")
    if report["classes"]:
        lines.append(
            f"{'class':<6} {'n':>4} {'attained':>9} {'shed':>5} "
            f"{'ttft p50':>10} {'ttft p99':>10} {'tpot p99':>10} "
            f"{'tok/s':>10} {'goodput':>10}")
    for prio, c in sorted(report["classes"].items()):
        def _f(v):
            return "-" if v is None else f"{v * 1e3:.1f}ms"
        att = ("-" if c["attained_frac"] is None
               else f"{c['attained']}/{c['n']}")
        lines.append(
            f"p{prio:<5} {c['n']:>4} {att:>9} {c['shed']:>5} "
            f"{_f(c['ttft_s']['p50']):>10} {_f(c['ttft_s']['p99']):>10} "
            f"{_f(c['tpot_s']['p99']):>10} "
            f"{c['tok_s']:>10,.1f} {c['goodput_tok_s']:>10,.1f}")
    return "\n".join(lines)
