"""Parallelism strategies built on the comm layer (SURVEY.md §2.2).

The reference's patterns are the HPC primitives ML parallelism is built
from; SURVEY.md §2.2 maps each and notes TP/PP/SP/ring-attention are
"absent as such — the ring + pt2pt components are their building blocks
and should be API-shaped so these can be layered on". This package is
that layering, TPU-first:

- :mod:`~.ring_attention` — context parallelism over a sequence-sharded
  mesh axis: the reference's ring exchange-and-accumulate dataflow
  (allreduce-mpi-sycl.cpp:173-182) with the accumulate generalized to
  online-softmax attention (SURVEY.md §5 "long-context").
- :mod:`~.ulysses` — all-to-all sequence parallelism (DeepSpeed-Ulysses
  style): heads scatter / sequence gather around local full attention.
- :mod:`~.tensor` — Megatron-style tensor parallelism: column/row
  sharded matmuls where the row-parallel reduction is the reference's
  allreduce (library ``psum`` or the hand ring, caller's choice).
- :mod:`~.pipeline` — pipeline-parallel stage handoff: the pairwise
  pt2pt pattern (SendRecvRing, allreduce-mpi-sycl.cpp:43-59) as a
  fill-drain microbatch schedule.

Everything is a rank-local function for use inside ``shard_map`` over a
named mesh axis, composable with dp/tp/sp/pp axes of one Mesh.
"""

from hpc_patterns_tpu.parallel.ring_attention import ring_attention  # noqa: F401
from hpc_patterns_tpu.parallel.ulysses import ulysses_attention  # noqa: F401
from hpc_patterns_tpu.parallel.tensor import (  # noqa: F401
    column_parallel,
    row_parallel,
    tp_mlp,
)
from hpc_patterns_tpu.parallel.pipeline import (  # noqa: F401
    pipeline_forward,
    pipeline_train_1f1b,
    schedule_1f1b,
)
