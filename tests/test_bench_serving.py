"""The engine-vs-static comparison runs in CI (tier-1): the
continuous-batching engine with the production levers on (bucketed
admission, overlapped prefill) must BEAT static batching on the mixed
prompt-length / long-tail-budget workload — the reference's discipline
that every binary measures its own overlap claim and FAILs when the
concurrent path doesn't clear the bound (omp_con.cpp's PASS bar),
applied to serving. The smoke shape lives in
benchmarks/bench_serving.smoke_config (one definition for the CLI and
this test); run_bench itself asserts the token-exactness oracle and
the warm-engine no-recompile invariant before returning numbers."""

import sys
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))


def test_smoke_engine_beats_static_on_mixed_workload():
    from benchmarks.bench_serving import run_bench, smoke_config

    r = run_bench(**smoke_config(), quiet=True)
    # the measured margin on this shape is ~2.5x; > 1.0 leaves the
    # whole margin as shield against shared-host load spikes (run_bench
    # already takes min-of-reps per mode)
    assert r["speedup"] > 1.0, (
        f"engine did not beat static batching: {r['speedup']:.3f}x "
        f"(static {r['t_static']:.2f}s, engine {r['t_engine']:.2f}s)")
    # the compile-count observable the bucket ladder exists for
    assert r["prefill_compiles"] <= r["ladder"]
    assert 0.0 <= r["bubble_frac"] <= 1.0
    assert r["distinct_lengths"] > r["ladder"] or r["ladder"] >= 2


def test_smoke_chaos_scenario_still_beats_static_and_reports_goodput():
    # the ROBUSTNESS gate (round 8): under a seeded stalled-host
    # injection and structural page starvation (preemption-and-resume
    # fires by construction), the engine must STILL beat clean static
    # batching — and the row must report goodput (SLO-attained tok/s)
    # next to raw tok/s. run_scenario itself asserts the degraded-path
    # oracle (every served row, preempted-and-resumed included, is
    # token-exact vs standalone) before returning any number.
    from benchmarks.bench_serving import run_scenario, scenario_smoke_config

    r = run_scenario(**scenario_smoke_config(), quiet=True)
    assert r["speedup"] > 1.0, (
        f"engine under chaos did not beat clean static: "
        f"{r['speedup']:.3f}x (static {r['t_static']:.2f}s, engine "
        f"{r['t_engine']:.2f}s)")
    # the injected faults actually fired (a chaos run that injected
    # nothing proves nothing) and preemption actually happened
    assert r["stall_injections"] == 2
    assert r["preemptions"] >= 1
    # goodput is reported and can never exceed raw throughput
    assert 0.0 < r["goodput_tok_s"] <= r["tokens_per_s_engine"] + 1e-6
    assert r["attained_frac"] is not None
    assert r["prefill_compiles"] <= r["ladder"]
    assert 0.0 <= r["bubble_frac"] <= 1.0


def test_smoke_plane_row_reports_goodput_and_migration_overlap():
    # the SERVING-PLANE gate (round 10): one open-loop stream through
    # a single engine, a 2-replica router plane, and the disaggregated
    # 1-prefill/1-decode plane. run_plane itself asserts the
    # disaggregation oracle (every served row — migrated rows included
    # — token-exact vs standalone) and that the FIT ladder never pads
    # worse than the default, before returning any number.
    from benchmarks.bench_serving import plane_smoke_config, run_plane

    config = plane_smoke_config()
    r = run_plane(**config, quiet=True)
    # every request actually crossed the KV handoff on the 1p/1d leg
    assert r["migrations"] >= config["n"]
    assert r["shed"] == 0
    assert r["plane_goodput_tok_s"] > 0
    assert r["disagg_goodput_tok_s"] > 0
    # the overlap floor: the measured share of migration-window time
    # hidden under an in-flight decode chunk. ~25-35% on this shape;
    # 0.05 leaves the margin as shield against shared-host noise (the
    # first handoff of a wave is legitimately exposed — cold start)
    assert r["kv_migration_overlap_frac"] >= 0.05, (
        f"KV migration did not overlap the decode chunk: "
        f"{r['kv_migration_overlap_frac']:.1%}")
    assert r["expected_padding_fit"] <= r["expected_padding_default"]
    # default transport: nothing rode (or claimed to ride) the DMA
    # tier, and the host-sharing note is off (no device placement)
    assert r["migration_transport"] == "device_put"
    assert r["dma_migration_overlap_frac"] is None
    assert r["placement_shares_host"] is False
    assert r["migration_bytes_per_round"] > 0


def test_smoke_plane_row_dma_transport_and_placement_note():
    # the round-17 transport row: --migration dma routes every 1p/1d
    # handoff over the fused paired remote-DMA kernel (per-device
    # placement forced), stays oracle-exact (run_plane asserts it),
    # reports the DMA-only overlap ledger, and — because the CPU
    # mesh's devices are virtual shards of one host — says so loudly
    # instead of letting the numbers impersonate a chip result
    import jax

    from benchmarks.bench_serving import (
        devices_share_host,
        plane_smoke_config,
        run_plane,
    )

    r = run_plane(**plane_smoke_config(), migration="dma", quiet=True)
    assert r["migration_transport"] == "dma"
    # every bundle rode the kernel — no silent fallback
    assert set(r["migration_transports"]) == {"dma"}
    assert r["migration_transports"]["dma"] == r["migrations"]
    assert r["dma_migration_overlap_frac"] is not None
    assert r["migration_bytes_per_round"] > 0
    # the satellite-4 pin: forced placement on the CPU mesh IS
    # host-shared, and the result says so
    assert devices_share_host(jax.devices()) is True
    assert r["placement_shares_host"] is True
    assert devices_share_host([]) is False
    assert devices_share_host(jax.devices()[:1]) is False


def test_smoke_offload_row_forces_eviction_and_reports_overlap():
    # the TIERED-MEMORY gate (round 11): the same stream through an
    # all-HBM engine and an engine whose HBM pool is HALF the working
    # set, fronting a host pool via the residency manager. run_offload
    # itself asserts the capacity oracle (constrained engine
    # token-identical to all-HBM AND to standalone paged_generate) and
    # that the cap forced REAL paging, before returning any number.
    from benchmarks.bench_serving import offload_smoke_config, run_offload

    r = run_offload(**offload_smoke_config(), quiet=True)
    assert r["hbm_pool"] < r["full_pool"]
    assert r["swap_outs"] > 0 and r["swap_ins"] > 0
    assert r["prefetch_bytes"] > 0
    # goodput is reported and can never exceed raw throughput
    assert 0.0 < r["offload_goodput_tok_s"] \
        <= r["tokens_per_s_tiered"] + 1e-6
    # the overlap is a measurement in [0, 1]; on this shape the pulls
    # land ~25-35% under the chunk — 0.02 leaves the margin as noise
    # shield (the CPU host tier is a same-memory copy, so the floor is
    # about scheduling, not DMA rates; the chip row is the real number)
    assert 0.02 <= r["prefetch_overlap_frac"] <= 1.0, (
        f"prefetch never overlapped the decode chunk: "
        f"{r['prefetch_overlap_frac']:.1%}")
    assert 0.0 <= r["bubble_frac"] <= 1.0


def test_smoke_shared_row_skips_prefill_and_reports_goodput():
    # the PREFIX-SHARING gate (round 12): one template/conversation-
    # tree stream through a private-pages engine and the sharing-aware
    # arena. run_shared itself asserts the sharing oracle (BOTH engines
    # token-identical to standalone paged_generate per request) and the
    # skip-fraction floor before returning any number — this test pins
    # the reported shape of the two gated keys.
    from benchmarks.bench_serving import run_shared, shared_smoke_config

    r = run_shared(**shared_smoke_config(), quiet=True)
    # the ISSUE's headline floor, re-asserted on the captured key
    assert r["prefill_skip_frac"] > 0.3, (
        f"radix match skipped only {r['prefill_skip_frac']:.1%} of "
        "prompt tokens on the template mix")
    assert r["prefix_hits"] > 0
    # goodput is reported and can never exceed raw throughput
    assert 0.0 < r["shared_goodput_tok_s"] \
        <= r["tokens_per_s_shared"] + 1e-6
    assert 0.0 < r["private_goodput_tok_s"]
    # the sharing rungs are page-aligned by construction
    assert all(b % 16 == 0 for b in r["ladder"])
    assert 0.0 <= r["bubble_frac"] <= 1.0


def test_smoke_quantized_row_reports_goodput_and_pool_bytes():
    # the QUANTIZED-DECODE gate (round 13): the smoke stream through a
    # compute-dtype baseline and an int8-KV engine. run_quantized
    # itself runs BOTH oracles (token-identical to standalone decode
    # within the precision; the teacher-forced precision law across
    # precisions) before returning any number — this test pins the
    # reported shape of the gated keys and the ISSUE's capacity floor.
    from benchmarks.bench_serving import (
        quantized_smoke_config,
        run_quantized,
    )

    r = run_quantized(**quantized_smoke_config(), quiet=True)
    assert r["kv_dtype"] == "int8"
    # the acceptance floor: quantized pool bytes <= 0.55x the bf16
    # pool at equal residents (measured from real allocations)
    assert r["kv_pool_bytes_frac"] <= 0.55, r["kv_pool_bytes_frac"]
    assert 0.0 < r["quant_goodput_tok_s"] \
        <= r["tokens_per_s_quant"] + 1e-6
    assert 0.0 < r["baseline_goodput_tok_s"]
    # the law values the oracle already gated on are reported
    assert r["greedy_agreement"] >= 0.85
    assert r["tv_mean"] <= 0.05
    assert 0.0 <= r["quant_bubble_frac"] <= 1.0


def test_smoke_elastic_row_beats_static_and_reports_efficiency():
    # the ELASTIC-PLANE gate (round 14): a diurnal ramp under seeded
    # replica-death chaos through the fixed 2-replica plane and the
    # autoscaled ElasticServingPlane. run_elastic itself asserts the
    # whole robustness contract before returning any number — the
    # death fault fired on both legs and did real damage, the static
    # plane sheds while the elastic plane serves everything, elastic
    # attainment strictly exceeds static, every served stream is
    # byte-exact vs standalone decode (greedy AND sampled via the
    # key-state checkpoint), and warm spin-up beat a cold init. This
    # test pins the reported shape of the gated keys.
    from benchmarks.bench_serving import elastic_smoke_config, run_elastic

    r = run_elastic(**elastic_smoke_config(), quiet=True)
    # the gated pair exists and points the right way
    assert r["elastic_slo_attainment"] > r["static_slo_attainment"]
    assert 0.0 < r["elastic_slo_attainment"] <= 1.0
    assert r["goodput_per_replica_round"] > 0.0
    # the degraded-mode accounting: static shed on the death, the
    # elastic plane absorbed it with resumes + a warm spin-up
    assert r["static_shed_on_death"] >= 1
    assert r["elastic_shed_on_death"] == 0
    assert r["spinups"] >= 1 and r["resumed"]
    assert r["sampled_resumed"]  # the sampled leg's death also resumed
    # warm spin-up measurably beat the cold init it replaces
    assert 0.0 < r["warm_spinup_s"] < r["cold_init_s"]
    # per-class attainment: the autoscaled plane is no worse in ANY
    # class and strictly better overall (asserted above)
    for prio, pair in r["per_class_attainment"].items():
        if pair["static"] is not None and pair["elastic"] is not None:
            assert pair["elastic"] >= pair["static"], (prio, pair)


def test_smoke_slo_budget_row_blames_the_injected_mechanism():
    # the SEGMENT-BUDGET gate (round 20): a seeded slow_host_transfer
    # through a thrashing 2-resident tier must breach the
    # prefetch_wait budget line and NO other — chaos lands in the
    # bucket it was injected into, nothing smears. run_slo_budget
    # asserts the breach set, the nonzero inter-token stall share,
    # and the oracle in-run; this pins the reported gate keys.
    from benchmarks.bench_serving import (
        run_slo_budget,
        slo_budget_smoke_config,
    )

    r = run_slo_budget(**slo_budget_smoke_config(), quiet=True)
    assert r["budget_breach_segments"] == ["prefetch_wait"]
    assert r["budget_breaches"] == 1
    assert 0.0 < r["tpot_p99_stall_share"] <= 1.0
    assert r["attribution_coverage_frac"] >= 0.95
    # the chaos actually did damage worth attributing: every pull ate
    # the injected delay and the injected time dominates a clean serve
    assert r["swap_outs"] > 0
    assert r["stall_injections"] >= r["swap_outs"]
    assert r["stall_injected_s"] > 0.0
