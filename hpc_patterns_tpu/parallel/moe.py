"""Mixture-of-experts with expert parallelism (EP) over a mesh axis.

Completes the parallelism menu of SURVEY.md §2.2 (EP listed as a
strategy the ring/pt2pt/collective primitives must be shaped for). The
communication pattern is the ``MPI_Alltoall`` the comm layer already
exposes (collectives.all_to_all — the same primitive as Ulysses): each
rank owns E/P experts; tokens are routed top-1 (Switch style), packed
into fixed ``capacity`` slots per (source rank, expert) — static shapes,
the XLA ground rule — exchanged with one all-to-all each way, processed
by the local experts' FFNs (batched einsum, MXU-shaped), and combined
with the router gates.

Drop semantics: tokens past an expert's per-source-rank capacity are
dropped (output contribution zero), exactly as in the dense oracle
:func:`moe_dense` with the same capacity — sharded and dense results are
numerically identical per token shard, which is what the §4.2-style
oracle test asserts.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from hpc_patterns_tpu.comm import collectives, ring


def _dispatch_combine(x, router_w, n_experts: int, capacity: int):
    """Top-1 routing tensors for local tokens x: (N, D).

    Returns (dispatch (N, E, C) f32 0/1, combine (N, E, C) f32 gate,
    aux_loss scalar). Position within an expert's capacity is assigned
    in token order (cumsum), the Switch transformer formulation.
    """
    n = x.shape[0]
    logits = jnp.dot(x.astype(jnp.float32), router_w.astype(jnp.float32))
    gates = jax.nn.softmax(logits, axis=-1)  # (N, E)
    expert = jnp.argmax(gates, axis=-1)  # (N,)
    onehot = jax.nn.one_hot(expert, n_experts, dtype=jnp.float32)  # (N, E)
    # slot index of each token within its expert (0-based, token order)
    position = jnp.cumsum(onehot, axis=0) * onehot - 1.0  # (N, E), -1 elsewhere
    kept = onehot * (position < capacity)  # overflow dropped
    pos_clamped = jnp.clip(position, 0, capacity - 1).astype(jnp.int32)
    slot_onehot = jax.nn.one_hot(pos_clamped, capacity, dtype=jnp.float32)
    dispatch = kept[..., None] * slot_onehot  # (N, E, C)
    top_gate = jnp.sum(gates * onehot, axis=-1)  # (N,)
    combine = dispatch * top_gate[:, None, None]
    # Switch load-balancing auxiliary loss: E * sum_e f_e * P_e
    f = onehot.mean(axis=0)
    p = gates.mean(axis=0)
    aux = n_experts * jnp.sum(f * p)
    return dispatch, combine, aux


def _expert_ffn(xin, w1, w2, activation=None):
    """Batched per-expert FFN: xin (E, C, D), w1 (E, D, F), w2 (E, F, D)."""
    act = activation or jax.nn.gelu
    h = act(jnp.einsum("ecd,edf->ecf", xin, w1.astype(xin.dtype)))
    return jnp.einsum("ecf,efd->ecd", h, w2.astype(xin.dtype))


def default_capacity(n_tokens: int, n_experts: int,
                     capacity_factor: float = 1.25) -> int:
    return max(1, int(n_tokens * capacity_factor / n_experts))


def moe_dense(x, router_w, w1, w2, *, capacity: int, activation=None):
    """Single-device oracle: all E experts local. x: (N, D); w1: (E, D,
    F); w2: (E, F, D). Returns (y (N, D), aux_loss)."""
    E = w1.shape[0]
    dispatch, combine, aux = _dispatch_combine(x, router_w, E, capacity)
    # routing math stays f32; dispatch/FFN run in x's (MXU-native) dtype
    xin = jnp.einsum("nec,nd->ecd", dispatch.astype(x.dtype), x)
    out = _expert_ffn(xin, w1, w2, activation)
    y = jnp.einsum("nec,ecd->nd", combine.astype(x.dtype), out)
    return y.astype(x.dtype), aux


def moe_ep(x, router_w, w1_local, w2_local, *, axis: str, capacity: int,
           activation=None):
    """Expert-parallel MoE layer (rank-local; run inside ``shard_map``).

    ``x``: (N_local, D) this rank's tokens. ``w1_local``/``w2_local``:
    (E/P, D, F)/(E/P, F, D) — this rank's expert shard. ``router_w``:
    (D, E) replicated. Two all-to-alls move (tokens→experts→tokens),
    riding ICI like every other collective in the framework (§2.3).
    Per-token results equal :func:`moe_dense` on the same token shard
    with the same capacity.
    """
    P = ring.axis_size(axis)
    e_local = w1_local.shape[0]
    E = e_local * P
    dispatch, combine, aux = _dispatch_combine(x, router_w, E, capacity)
    xin = jnp.einsum("nec,nd->ecd", dispatch.astype(x.dtype), x)  # (E, C, D)
    # tokens to their experts' owners: (E, C, D) -> (E/P, P*C, D)
    xin = collectives.all_to_all(xin, axis, split_axis=0, concat_axis=1)
    out = _expert_ffn(xin, w1_local, w2_local, activation)
    # results back to the tokens' owners: (E/P, P*C, D) -> (E, C, D)
    out = collectives.all_to_all(out, axis, split_axis=1, concat_axis=0)
    y = jnp.einsum("nec,ecd->nd", combine.astype(x.dtype), out)
    # aux is per-shard; average across ranks for a global scalar
    aux = collectives.allreduce(aux, axis, "mean")
    return y.astype(x.dtype), aux
