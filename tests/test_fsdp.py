"""FSDP (ZeRO-3 style) tests: params/grads/optimizer state sharded over
the fsdp axis, batch over (dp, fsdp) — pure GSPMD, the sharded result
must equal the single-device oracle (SURVEY.md §4.2 style)."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from hpc_patterns_tpu import topology
from hpc_patterns_tpu.models import TransformerConfig, init_params, loss_fn
from hpc_patterns_tpu.models.sharding import param_shardings, shard_params
from hpc_patterns_tpu.models.train import (
    init_train_state,
    make_batch,
    make_train_step,
)

TINY = dict(vocab=64, d_model=32, n_heads=4, n_layers=2, d_ff=64,
            max_seq=16, dtype="float32")


def _tokens(key, b=8, t=16):
    return jax.random.randint(key, (b, t), 0, 64, "int32")


class TestFSDP:
    def test_params_actually_sharded(self):
        cfg = TransformerConfig(**TINY, fsdp=True)
        mesh = topology.make_mesh({"fsdp": 8})
        params, opt_state = init_train_state(jax.random.PRNGKey(0), cfg, mesh)
        w1 = params["layers"]["w1"]  # (L, D, F) with D over fsdp
        shard = w1.addressable_shards[0].data
        assert shard.shape == (2, 32 // 8, 64), shard.shape
        # optax moments inherit the sharding (ZeRO: no replicated state)
        mu_w1 = jax.tree.leaves(
            jax.tree.map(lambda x: x.sharding, opt_state)
        )
        specs = {str(s.spec) for s in mu_w1 if hasattr(s, "spec")}
        assert any("fsdp" in s for s in specs), specs

    @pytest.mark.parametrize("axes,extra", [
        ({"fsdp": 8}, {}),                       # pure ZeRO
        ({"dp": 2, "fsdp": 4}, {}),              # hybrid sharded-data
        ({"fsdp": 4, "tp": 2}, {}),              # fsdp x tensor parallel
        ({"fsdp": 2, "sp": 2, "tp": 2},
         {"attention": "ring_flash"}),           # fsdp x sp ring
    ])
    def test_loss_matches_single_device(self, axes, extra):
        cfg_local = TransformerConfig(**{**TINY, **extra})
        cfg = TransformerConfig(**{**TINY, **extra}, fsdp=True)
        params = init_params(jax.random.PRNGKey(0), cfg_local)
        tokens = _tokens(jax.random.PRNGKey(1))
        want = float(loss_fn(params, tokens, cfg_local))

        mesh = topology.make_mesh(axes)
        p_sharded = shard_params(params, mesh, cfg)
        got = jax.jit(lambda p, t: loss_fn(p, t, cfg, mesh))(p_sharded, tokens)
        np.testing.assert_allclose(float(got), want, rtol=2e-5)

    def test_grads_match_single_device(self):
        cfg_local = TransformerConfig(**TINY)
        cfg = TransformerConfig(**TINY, fsdp=True)
        params = init_params(jax.random.PRNGKey(0), cfg_local)
        tokens = _tokens(jax.random.PRNGKey(1))
        want = jax.grad(lambda p: loss_fn(p, tokens, cfg_local))(params)

        mesh = topology.make_mesh({"fsdp": 8})
        p_sharded = shard_params(params, mesh, cfg)
        # out_shardings pinned to the param layout: the gradient sync
        # lowers to reduce-scatter, not all-reduce + replicate (the
        # ZeRO property). Inside make_train_step the optimizer's
        # donated sharded state pins this implicitly; a standalone
        # grad call must pin it explicitly or GSPMD may replicate.
        got = jax.jit(
            jax.grad(lambda p: loss_fn(p, tokens, cfg, mesh)),
            out_shardings=param_shardings(mesh, cfg),
        )(p_sharded)
        assert "fsdp" in str(got["layers"]["w1"].sharding.spec)
        for a, b in zip(jax.tree.leaves(got), jax.tree.leaves(want)):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       atol=2e-5)

    def test_train_step_learns(self):
        cfg = TransformerConfig(**TINY, fsdp=True)
        mesh = topology.make_mesh({"dp": 2, "fsdp": 4})
        params, opt = init_train_state(jax.random.PRNGKey(0), cfg, mesh)
        step = make_train_step(cfg, mesh)
        tokens = make_batch(jax.random.PRNGKey(1), cfg, 8, 16, mesh)
        losses = []
        for _ in range(4):
            loss, params, opt = step(params, opt, tokens)
            losses.append(float(loss))
        assert losses[-1] < losses[0], losses
        # params stayed sharded through the update
        assert "fsdp" in str(params["layers"]["w1"].sharding.spec)

    def test_fsdp_as_dp_single_axis(self):
        # axis_fsdp = "dp": classic ZeRO over the data ranks, one axis
        cfg_local = TransformerConfig(**TINY)
        cfg = TransformerConfig(**TINY, fsdp=True, axis_fsdp="dp")
        params = init_params(jax.random.PRNGKey(0), cfg_local)
        tokens = _tokens(jax.random.PRNGKey(1))
        want = float(loss_fn(params, tokens, cfg_local))

        mesh = topology.make_mesh({"dp": 8})
        p_sharded = shard_params(params, mesh, cfg)
        assert "dp" in str(p_sharded["layers"]["w1"].sharding.spec)
        got = jax.jit(lambda p, t: loss_fn(p, t, cfg, mesh))(p_sharded, tokens)
        np.testing.assert_allclose(float(got), want, rtol=2e-5)

    def test_specs_without_fsdp_unchanged(self):
        cfg = TransformerConfig(**TINY)
        mesh = topology.make_mesh({"dp": 8})
        sh = param_shardings(mesh, cfg)
        assert "fsdp" not in str(jax.tree.leaves(sh)[0].spec)
