"""Auto-tuning / load balancing (C12 in SURVEY.md §2.1).

Concurrency is only measurable when the commands take comparable time:
if one command dominates, the theoretical speedup collapses toward 1 and
the verdict warns "unbalanced" (sycl_con.cpp:282-283). The reference
balances in two moves, reproduced here:

1. shrink the larger of the two copy sizes by the measured time ratio
   (sycl_con.cpp:243-255) — :func:`balance_copy_sizes`;
2. pick the compute tripcount so kernel time ≈ mean copy time, assuming
   T(tripcount) is linear (sycl_con.cpp:257-268) —
   :func:`tune_tripcount`, with one refinement pass since the linearity
   assumption has a constant launch-overhead term the reference ignores.

All probes use the standard timing protocol (warmup + min-of-reps) so
XLA compilation never contaminates a tuning decision (§7 hard part (d)).
"""

from __future__ import annotations

from hpc_patterns_tpu.concurrency.commands import (
    ComputeCommand,
    CopyD2MCommand,
    CopyM2DCommand,
)
from hpc_patterns_tpu.concurrency.engine import bench

_PROBE_REPS = 5


def _time_command(cmd, repetitions=_PROBE_REPS) -> float:
    return bench("serial", [cmd], repetitions=repetitions, warmup=1).total.min_s


def balance_copy_sizes(
    m2d_elements: int,
    d2m_elements: int,
    device=None,
    *,
    min_elements: int = 1 << 10,
) -> tuple[int, int, dict]:
    """Equalize M2D and D2M durations by shrinking the slower direction's
    size by the measured time ratio (sycl_con.cpp:243-255 shrinks the
    *larger-time* global size). Returns (m2d_elements, d2m_elements,
    probe_info)."""
    t_m2d = _time_command(CopyM2DCommand(m2d_elements, device))
    t_d2m = _time_command(CopyD2MCommand(d2m_elements, device))
    info = {"t_m2d_s": t_m2d, "t_d2m_s": t_d2m}
    if t_m2d <= 0 or t_d2m <= 0:
        return m2d_elements, d2m_elements, info
    if t_m2d > t_d2m:
        m2d_elements = max(min_elements, int(m2d_elements * t_d2m / t_m2d))
    else:
        d2m_elements = max(min_elements, int(d2m_elements * t_m2d / t_d2m))
    info["m2d_elements"] = m2d_elements
    info["d2m_elements"] = d2m_elements
    return m2d_elements, d2m_elements, info


def tune_tripcount_to_copies(
    copy_commands,
    *,
    compute_elements: int = 8 * 128,
    device=None,
    min_target_s: float = 1e-4,
) -> tuple[int, dict]:
    """The full C12 compute-balance step: probe each copy command, target
    the *mean* copy time (sycl_con.cpp:257-268 targets the copy-time
    mean), and tune the tripcount to it. Keeps the whole policy —
    probing protocol included — in this module."""
    if not copy_commands:
        raise ValueError("need at least one copy command to balance against")
    target = sum(_time_command(c) for c in copy_commands) / len(copy_commands)
    return tune_tripcount(
        max(target, min_target_s),
        compute_elements=compute_elements,
        device=device,
    )


def tune_tripcount(
    target_s: float,
    *,
    compute_elements: int = 8 * 128,
    device=None,
    probe_tripcount: int = 256,
    max_tripcount: int = 1 << 24,
) -> tuple[int, dict]:
    """Tripcount such that the compute command runs ~``target_s``,
    assuming linear T(tripcount) (sycl_con.cpp:257-268), then one
    refinement probe at the predicted value."""
    if target_s <= 0:
        raise ValueError("target_s must be positive")
    cmd = ComputeCommand(compute_elements, probe_tripcount, device)
    t1 = _time_command(cmd)
    trip = max(1, min(max_tripcount, int(probe_tripcount * target_s / max(t1, 1e-9))))
    cmd.tripcount = trip
    t2 = _time_command(cmd)
    refined = max(1, min(max_tripcount, int(trip * target_s / max(t2, 1e-9))))
    info = {
        "probe_tripcount": probe_tripcount,
        "probe_s": t1,
        "predicted_tripcount": trip,
        "predicted_s": t2,
        "tripcount": refined,
    }
    return refined, info
