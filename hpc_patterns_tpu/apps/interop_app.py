"""Interop proof app — the rebuild of ``interop_omp_ze_sycl`` (C10).

The reference's main() proves zero-copy both directions between two
runtimes sharing one device context: an OMP-allocated buffer filled by
an OMP kernel is read by a SYCL memcpy, and a SYCL-allocated buffer is
read by an OMP kernel, each validated by asserts
(interop_omp_ze_sycl.cpp:70-104).

Here the runtime pair is {native C++ allocator, numpy} ↔ {JAX} ↔
{torch}, over the dlpack protocol:

1. native → JAX: C++ ``hp_iota`` fills an aligned allocation; JAX reads
   it through dlpack; **zero-copy asserted by pointer identity** (the
   airtight form of the reference's value asserts) + value oracle.
2. JAX → torch → JAX: a JAX computation's output crosses to torch and
   back, pointer-identical, value-validated in C (``hp_validate``).
3. foreign memory → accelerator: the native buffer staged to the
   default (TPU) device and back, value-validated — the boundary that
   is a DMA by physics (the reference's analog stops at one GPU's
   context; crossing memory spaces is the concurrency suite's M2D).

Prints per-direction "Passed <n>" lines and a SUCCESS/FAILURE verdict.
"""

from __future__ import annotations

import sys

import numpy as np

import jax
import jax.numpy as jnp

from hpc_patterns_tpu.harness import RunLog, Verdict
from hpc_patterns_tpu.harness.cli import base_parser
from hpc_patterns_tpu.interop import native, zero_copy


def build_parser():
    p = base_parser(__doc__.splitlines()[0])
    p.add_argument("-n", "--elements", type=int, default=1 << 16)
    p.add_argument("--alignment", type=int, default=128,
                   help="native allocation alignment (reference ALIGNMENT=128)")
    return p


def run(args) -> int:
    log = RunLog(args.log, truncate=not args.log_append)
    checks: list[tuple[str, bool]] = []

    if not native.available() and not native.build():
        log.print("SKIP: native library unavailable (make -C native failed)")
        log.print("FAILURE")
        return 1

    n = args.elements

    # 1. native C++ -> numpy -> JAX, zero-copy (≙ OMP fill, SYCL read)
    buf = native.AlignedBuffer(n, alignment=args.alignment)
    buf.iota(0.0, 1.0)
    arr, zc = zero_copy.native_to_jax(buf)
    values_ok = bool(
        jnp.all(arr == jnp.arange(n, dtype=jnp.float32)).item()
    )
    checks.append(("native->jax zero-copy", zc))
    checks.append(("native->jax values", values_ok))

    # 2. JAX compute -> torch -> JAX, zero-copy both hops (≙ SYCL alloc,
    #    OMP kernel read). Result validated by the C oracle.
    doubled = jax.jit(lambda x: x * 2.0)(
        jax.device_put(jnp.ones((n,), jnp.float32), jax.devices("cpu")[0])
    )
    doubled = jax.block_until_ready(doubled)
    try:
        t, zc_jt = zero_copy.jax_to_torch(doubled)
        back, zc_tj = zero_copy.torch_to_jax(t)
        out = native.AlignedBuffer(n, alignment=args.alignment)
        out.as_numpy()[:] = np.from_dlpack(back)
        checks.append(("jax->torch zero-copy", zc_jt))
        checks.append(("torch->jax zero-copy", zc_tj))
        checks.append(("C-oracle validation", out.validate(2.0) == -1))
    except ImportError:
        # torch is the stand-in second runtime; without it the leg is
        # unprovable, not failed (mirrors the reference's per-runtime
        # precondition guards)
        log.print("SKIP: torch unavailable, torch bridge legs skipped")

    # 3. native memory -> accelerator and back (staged: DMA by physics)
    dev = jax.devices(args.backend)[0] if args.backend else jax.devices()[0]
    staged = jax.device_put(buf.as_numpy(), dev)
    tripled = np.asarray(jax.jit(lambda x: x * 3.0)(staged))
    # compare in f32 with tolerance: exact f64 equality would fail for
    # n past 2^24 purely from float32 rounding
    expect_last = np.float32(3.0) * np.float32(n - 1)
    checks.append(
        (f"native->{dev.platform} roundtrip",
         bool(np.isclose(tripled[-1], expect_last, rtol=1e-6)))
    )

    all_ok = all(ok for _, ok in checks)
    for i, (name, ok) in enumerate(checks):
        log.print(f"{'Passed' if ok else 'FAILED'} {i} ({name})")
    log.emit(kind="result", name="interop", success=all_ok,
             checks={name: ok for name, ok in checks}, elements=n)
    verdict = Verdict(success=all_ok, messages=("SUCCESS" if all_ok else "FAILURE",))
    log.print(verdict.summary_line())
    return verdict.exit_code


def main(argv=None) -> int:
    return run(build_parser().parse_args(argv))


if __name__ == "__main__":
    sys.exit(main())
