"""Radix prefix cache: the page-granular index behind KV sharing.

Millions of users means shared system prompts, few-shot templates, and
conversation trees — the hottest KV bytes in the serving arena are the
SAME bytes, prefilled N ways into private pages. This module is the
index that lets the paged arena share them: a radix/trie over admitted
token prompts at PAGE granularity (one node = one full page of prompt
tokens = one pool page id), so admission can longest-prefix-match a
new prompt against everything already resident, map the matched pages
read-only into the new row's table, and prefill only the tail
(``models/serving.py``'s sharing-aware admission;
``models/decode.paged_tail_prefill`` is the compute half).

Design points, each load-bearing:

- **page-aligned nodes**: a node covers exactly ``page_size`` tokens,
  so a match IS a list of pool page ids — no partial-page bookkeeping,
  and the COW rule collapses to "decode never writes a page below the
  prompt's own tail" (docs/prefix_cache.md);
- **rung-keyed chains**: chains are scoped by the ADMISSION RUNG (the
  bucket-ladder length the prompt padded to). Prefix K/V is bitwise
  SUFFIX-independent under causal masking but NOT length-independent —
  XLA executables at different row counts disagree in ULPs on shared
  rows (measured: prefill(32) vs prefill(40), layer-1 K, ~1e-6) — so
  bytes written by a rung-R prefill are exactly what a same-rung
  reader's private prefill would have written, and nothing else is.
  A cross-rung reader simply misses (and inserts its own chain);
- **refcounts live in the arena, not here**: the cache is a pure host
  index. The serving engine owns page refcounts; the cache reports
  which pages it references and calls back into the arena when nodes
  are evicted. One owner of truth for "is this page free".

The engine-facing surface: :meth:`RadixPrefixCache.match` (longest
cached chain for a prompt; ``touch=False`` is the sizing peek that
leaves LRU stamps alone), :meth:`count_match` (fold an admission's
outcome into the hit/miss observables), :meth:`insert` (extend a
chain with newly prefilled full-prompt pages; stamps the traversed
chain — how an admission marks its chain hot), :meth:`evict` (free
LRU leaf pages under arena pressure — only nodes whose page no row
maps, the refcount-1 rule), :meth:`has_page` / :meth:`pages`
(membership, for the pin-while-shared and swap logic).

Import-light (numpy only): unit-testable without jax, like loadgen.
"""

from __future__ import annotations

import heapq
from dataclasses import dataclass, field
from typing import Callable, Sequence

import numpy as np


@dataclass
class _Node:
    """One cached page: ``key`` is the page's ``page_size`` tokens
    (canonical int32 little-endian bytes — the child-map key), ``page``
    the pool page id holding its K/V, ``rung`` the admission rung the
    bytes were computed at. Children extend the prompt by one page."""
    key: bytes
    page: int
    rung: int
    parent: "_Node | None"
    children: dict = field(default_factory=dict)
    last_touch: int = 0


class RadixPrefixCache:
    """The radix prefix index over one engine's paged arena."""

    def __init__(self, page_size: int):
        if page_size < 1:
            raise ValueError(f"page_size must be >= 1, got {page_size}")
        self.page_size = int(page_size)
        #: rung -> root children dict (a virtual root per rung)
        self._roots: dict[int, dict] = {}
        self._page_nodes: dict[int, _Node] = {}
        self._clock = 0
        # admission hit/miss observables, written ONLY by
        # :meth:`count_match` (the engine owns the token-volume
        # counters — serve.prefill_skip_tokens and prefill_skip_frac —
        # so the metric has one owner per layer)
        self.hits = 0
        self.misses = 0

    # -- internals ----------------------------------------------------------

    def _chunks(self, tokens) -> list[bytes]:
        t = np.ascontiguousarray(np.asarray(tokens, np.int32))
        P = self.page_size
        return [t[i * P:(i + 1) * P].tobytes()
                for i in range(len(t) // P)]

    def _tick(self) -> int:
        self._clock += 1
        return self._clock

    # -- engine surface -----------------------------------------------------

    def __len__(self) -> int:
        return len(self._page_nodes)

    def pages(self) -> set[int]:
        """Pool page ids this cache currently references (each holds
        one arena refcount)."""
        return set(self._page_nodes)

    def has_page(self, page: int) -> bool:
        return page in self._page_nodes

    def match(self, tokens, rung: int, *, max_pages: int | None = None,
              touch: bool = True) -> list[int]:
        """Longest cached chain for ``tokens`` at ``rung``: the page
        ids of the deepest root-anchored node path whose concatenated
        keys prefix ``tokens``, capped at ``max_pages`` (the engine
        caps at ``(len(tokens) - 1) // page_size`` so the tail always
        keeps the last prompt token — the first-token logits must be
        computed, not looked up). ``touch=True`` stamps the chain's
        LRU clock; the engine's sizing walks pass ``touch=False`` so
        a queued request that never admits cannot keep its chain
        artificially hot and skew eviction against admitting traffic
        (an admission stamps its chain through :meth:`insert`).
        Hit/miss accounting is separate (:meth:`count_match`) for the
        same reason."""
        chunks = self._chunks(tokens)
        if max_pages is not None:
            chunks = chunks[:max_pages]
        node_map = self._roots.get(int(rung), {})
        chain: list[_Node] = []
        for ch in chunks:
            node = node_map.get(ch)
            if node is None:
                break
            chain.append(node)
            node_map = node.children
        if touch:
            now = self._tick()
            for node in chain:
                node.last_touch = now
        return [n.page for n in chain]

    def count_match(self, n_pages: int) -> None:
        """Fold ONE admission's match outcome into the hit/miss
        observables — the engine's admission path walks the trie with
        :meth:`match` for its sizing/reclaim math and calls this only
        when the match actually becomes an admission, so candidates
        that were sized but never admitted don't inflate the hit
        rate. Token-volume accounting (the skip-frac counters) lives
        with the engine, which also sees migration installs."""
        if n_pages:
            self.hits += 1
        else:
            self.misses += 1

    def insert(self, tokens, rung: int, pages: Sequence[int]) -> list[int]:
        """Extend the rung's trie with the chain for ``tokens``:
        ``pages[i]`` holds page ``i``'s K/V. Existing nodes are kept
        (first writer wins — a same-pass duplicate admission's private
        page simply stays private); NEW nodes take a cache reference on
        their page, and the list of newly referenced page ids is
        returned so the ARENA can incref them (refcounts are the
        engine's, module docstring). ``len(pages)`` full pages of
        ``tokens`` must exist."""
        chunks = self._chunks(tokens)[:len(pages)]
        if len(chunks) < len(pages):
            raise ValueError(
                f"insert of {len(pages)} page(s) needs that many full "
                f"pages of tokens, got {len(chunks)}")
        node_map = self._roots.setdefault(int(rung), {})
        parent: _Node | None = None
        new_pages: list[int] = []
        now = self._tick()
        for ch, page in zip(chunks, pages):
            node = node_map.get(ch)
            if node is None:
                node = _Node(key=ch, page=int(page), rung=int(rung),
                             parent=parent, last_touch=now)
                node_map[ch] = node
                self._page_nodes[int(page)] = node
                new_pages.append(int(page))
            node.last_touch = now
            parent = node
            node_map = node.children
        return new_pages

    def evict(self, need_pages: int,
              may_evict: Callable[[int], bool]) -> list[int]:
        """Free up to ``need_pages`` pages by dropping LRU LEAF nodes
        (an interior node anchors its descendants' matches, so chains
        shrink from the tip). Only nodes whose page ``may_evict``
        approves are dropped — the engine passes ``refcount == 1``, so
        a page a resident row still maps (the hottest bytes) is never
        evicted, the ISSUE's shared-pages-are-pinned rule. Returns the
        freed page ids for the arena to decref (which frees them).

        One scan, not one per victim: current leaves heapify by
        (last_touch, page) and a parent enters the pool lazily when
        its last child drops — ``may_evict`` is stable across one call
        (refcounts only move after the arena decrefs the result), so a
        refused node stays refused and is popped exactly once."""
        freed: list[int] = []
        heap = [(n.last_touch, n.page)
                for n in self._page_nodes.values() if not n.children]
        heapq.heapify(heap)
        while heap and len(freed) < need_pages:
            _, page = heapq.heappop(heap)
            node = self._page_nodes.get(page)
            if node is None or node.children or not may_evict(page):
                continue
            parent = node.parent
            self._drop(node)
            freed.append(page)
            if parent is not None and not parent.children \
                    and parent.page in self._page_nodes:
                heapq.heappush(heap, (parent.last_touch, parent.page))
        return freed

    def _drop(self, node: _Node) -> None:
        if node.children:
            raise ValueError("evicting an interior node would orphan "
                             "its descendants' chains")
        siblings = (node.parent.children if node.parent is not None
                    else self._roots.get(node.rung, {}))
        siblings.pop(node.key, None)
        self._page_nodes.pop(node.page, None)

    def release_pages(self, pages: Sequence[int]) -> list[int]:
        """Drop the nodes holding ``pages`` (deepest-first so parents
        only go once their children have), EXCEPT nodes that still
        have cached descendants — those (and their ancestors) stay,
        and their pages stay allocated. Returns the page ids actually
        released (the arena decrefs exactly those). A targeted-release
        helper beside :meth:`clear`: the serving engine itself keeps
        cache references resident across swap-out BY DESIGN (shared
        pages stay in HBM and shareable while a row's private pages
        move — ``_row_swappable``), so nothing calls this on the hot
        path; it is for index surgery under explicit page-set
        invalidation (tests, future whole-tier drains)."""
        want = {int(p) for p in pages}
        released: list[int] = []
        # deepest-first: repeatedly drop childless wanted nodes
        progressed = True
        while progressed:
            progressed = False
            for p in list(want):
                node = self._page_nodes.get(p)
                if node is not None and not node.children:
                    self._drop(node)
                    released.append(p)
                    want.discard(p)
                    progressed = True
                elif node is None:
                    want.discard(p)
        return released

    def clear(self) -> list[int]:
        """Drop every node; returns all referenced pages (the arena
        decrefs them — an engine-teardown / test-drain helper)."""
        pages = sorted(self._page_nodes)
        self._roots.clear()
        self._page_nodes.clear()
        return pages
