"""Decode token-step benchmark: flash-decode kernel vs the XLA gather
path, at a controlled cache length.

Usage: python benchmarks/bench_decode.py [--prompt=N] [--kv=N]

Protocol: the cache is built once (flash-mode prefill — the gather
path's dense prefill cannot even run an 8k prompt), then each impl's
``decode_step`` is iterated inside ONE dispatch with ``lax.fori_loop``
(greedy token fed back, position advancing, cache updated in place) and
timed with the repo's tunnel-proof amortized protocol
(harness.timing.amortized_seconds) — dispatch/readback latency cancels,
leaving pure per-token device time. The prompt length sets the live
cache prefix: the flash kernel's HBM traffic scales with it; the
gather path's with the allocated max_len.
"""

import functools
import sys

import jax
import jax.numpy as jnp
from jax import lax

from hpc_patterns_tpu.harness.timing import amortized_seconds
from hpc_patterns_tpu.models import TransformerConfig
from hpc_patterns_tpu.models.decode import decode_step, prefill
from hpc_patterns_tpu.models.transformer import init_params


def arg(name, default, cast=int):
    for a in sys.argv[1:]:
        if a.startswith(f"--{name}="):
            return cast(a.split("=", 1)[1])
    return default


def main():
    on_tpu = jax.default_backend() == "tpu"
    prompt_len = arg("prompt", 8064 if on_tpu else 96)
    slack = arg("slack", 512 if on_tpu else 32)  # decode room in cache
    batch = arg("batch", 8 if on_tpu else 2)
    iters = arg("iters", 128 if on_tpu else 8)
    base = dict(
        vocab=arg("vocab", 32768 if on_tpu else 256),
        d_model=arg("d", 1024 if on_tpu else 64),
        n_heads=arg("heads", 8 if on_tpu else 4),
        n_layers=arg("layers", 8 if on_tpu else 2),
        d_ff=arg("ff", 4096 if on_tpu else 128),
        max_seq=prompt_len + slack,
        dtype="bfloat16" if on_tpu else "float32",
        n_kv_heads=arg("kv", 0),
        kv_cache_dtype=arg("cache", "compute", str),
    )
    impls = [a.split("=", 1)[1] for a in sys.argv[1:]
             if a.startswith("--impl=")] or ["flash", "gather"]

    cfg0 = TransformerConfig(**base, decode_attn="flash")
    params = init_params(jax.random.PRNGKey(0), cfg0)
    prompt = jax.random.randint(
        jax.random.PRNGKey(1), (batch, prompt_len), 0, cfg0.vocab, "int32"
    )
    logits, cache = jax.jit(
        lambda p, t: prefill(p, t, cfg0, prompt_len + slack)
    )(params, prompt)
    first = jnp.argmax(logits, axis=-1).astype(jnp.int32)
    jax.block_until_ready(cache)

    t_step = {}
    for impl in impls:
        if impl == "paged":
            # block-table cache: the pool allocates prompt+slack pages,
            # NOT the declared maximum — the capacity row runs where the
            # equivalent linear allocation would not fit
            from hpc_patterns_tpu.models.decode import (
                init_paged_cache,
                paged_decode_step,
                paged_prefill,
            )

            page = arg("page", 512 if on_tpu else 16)
            # pages fetched per kernel grid step (0 = the kernel's
            # auto: match the linear 2048-row block). --ppstep=1 is
            # the round-4 one-page-per-step form for the gap sweep
            ppstep = arg("ppstep", 0) or None
            pages = -(-(prompt_len + slack) // page)
            pcache = init_paged_cache(cfg0, batch, pages, page)
            _, pcache = jax.jit(
                lambda p, t, c: paged_prefill(p, t, cfg0, c, page)
            )(params, prompt, pcache)
            jax.block_until_ready(pcache)

            @functools.partial(jax.jit, static_argnums=(3,))
            def run_paged(params, cache, tok, n):
                def body(_, carry):
                    cache, pos, tok = carry
                    logits, cache = paged_decode_step(
                        params, cache, pos, tok, cfg0,
                        identity_layout=True, pages_per_step=ppstep,
                    )
                    nxt = jnp.argmax(logits, axis=-1).astype(jnp.int32)
                    return cache, pos + 1, nxt

                _, _, tok = lax.fori_loop(
                    0, n, body, (cache, jnp.int32(prompt_len), tok)
                )
                return tok

            t = amortized_seconds(
                lambda n: run_paged(params, pcache, first, n),
                iters=iters, repetitions=3, base_iters=iters // 2,
            )
            t_step[impl] = t
            pool_tok = pages * page
            print(f"impl=paged   pool={batch}x{pool_tok} (page {page}, "
                  f"ppstep {ppstep or 'auto'}) "
                  f"B={batch} kv={cfg0.kv_heads}: {t * 1e3:6.3f} "
                  f"ms/token-step ({batch / t:,.0f} tok/s)")
            continue
        cfg = TransformerConfig(**base, decode_attn=impl)

        @functools.partial(jax.jit, static_argnums=(3,))
        def run_n(params, cache, tok, n):
            def body(_, carry):
                cache, pos, tok = carry
                logits, cache = decode_step(params, cache, pos, tok, cfg)
                nxt = jnp.argmax(logits, axis=-1).astype(jnp.int32)
                return cache, pos + 1, nxt
            # position resets each call so the streamed prefix length is
            # constant across iteration counts (the differencing needs
            # per-step cost to be stationary)
            _, _, tok = lax.fori_loop(
                0, n, body, (cache, jnp.int32(prompt_len), tok)
            )
            return tok

        t = amortized_seconds(
            lambda n: run_n(params, cache, first, n),
            iters=iters, repetitions=3, base_iters=iters // 2,
        )
        t_step[impl] = t
        print(f"impl={impl:7s} cache={prompt_len} B={batch} "
              f"kv={cfg.kv_heads}: {t * 1e3:6.3f} ms/token-step "
              f"({batch / t:,.0f} tok/s)")
    if len(t_step) == 2:
        a, b = impls
        print(f"speedup {b}->{a}: {t_step[b] / t_step[a]:.2f}x")


if __name__ == "__main__":
    main()
