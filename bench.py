"""Headline benchmark: on-chip DMA/compute overlap speedup.

The reference's headline claim is concurrent-kernel/copy overlap on one
device (concurency/sycl_con.cpp; BASELINE.json "concurrent-kernel overlap
%"). The TPU-native equivalent measured here: a Pallas double-buffered
HBM→VMEM pipeline (compute on chunk i while chunk i+1's DMA flies) vs the
serialized wait-then-compute walk of the same work
(hpc_patterns_tpu/concurrency/pipeline.py).

Protocol (all on-device, honest through high-latency dispatch paths):
- per-pass times via completion-forced differencing
  (harness.timing.amortized_seconds) — dispatch/readback latency cancels;
- C12-style autotune: tripcount set so compute/pass ≈ DMA/pass
  (sycl_con.cpp:257-268's balance step);
- verdict per the reference rule: PASS iff speedup > theoretical/1.3
  (sycl_con.cpp:279-296).

Prints ONE JSON line:
  {"metric": "onchip_overlap_speedup", "value": <speedup>, "unit": "x",
   "vs_baseline": <speedup / (theoretical_max / 1.3)>}
vs_baseline >= 1.0 means the overlap beats the reference's own PASS bar.

``--gate``: capture as usual, write the result as the next
``BENCH_rNN.json`` round, then run the regression gate
(``python -m hpc_patterns_tpu.harness.regress``) over the trajectory —
exit nonzero if the new round degrades a headline metric beyond
tolerance. The re-grounding sequence (benchmarks/reground_r5.sh) ends
with this, so a perf regression can no longer land silently.
"""

import json
import os
import select
import signal
import subprocess
import sys
import time

# jax + the pipeline module are imported inside the measurement child
# under a watchdog: the axon TPU plugin registers at jax-import time,
# and a dead tunnel HANGS that import in C code (observed, not
# hypothetical) — an import at module top would hang before any guard
# can run, and a Python-level SIGALRM handler never fires while the
# interpreter is blocked inside the plugin's C connect loop. So the
# DEFAULT entry is a supervisor that runs the measurement in a child
# process and enforces the timeouts from outside.
jax = None
pipeline = None

_UP_SENTINEL = "HPCPAT_BENCH_UP"

# 16 x (2048, 128) f32 = 16 MiB working set. Fewer, larger chunks than
# the DMA-granularity minimum: the ~0.3 us/chunk loop+semaphore cost is
# amortized 4x, which measured 1.87x overlap (vs 1.50x at 64x512) and
# pushes per-chunk DMA to ~650 GB/s.
NUM_CHUNKS = 16
CHUNK_ROWS = 2048
# probe with enough compute that the differenced probe calls are
# device-time-dominated (~100 ms), not tunnel-latency noise — a near-zero
# probe reading would otherwise blow up the balanced tripcount
PROBE_TRIPS = 64
MAX_TRIPS = 4096


# measurement protocol (calibrated pass counts, jitter-proof
# differencing) lives in pipeline.per_pass_seconds, shared with the
# concurrency app's on-chip engine
CAL_PASSES = 1000

# session health: healthy chip sessions measure ~577-580 GB/s per-chunk
# DMA at this shape (rounds 1-2); round 3's capture ran at 512.6 GB/s —
# the same code, a ~10%-slow chip/tunnel session — and its 1.77x
# overlap read as a regression until the DMA telemetry was consulted.
# A capture whose dma_gbps falls >10% below nominal is flagged so the
# ratio is interpreted against a slow session, not the code.
NOMINAL_DMA_GBPS = 578.0


def per_pass_seconds(x, mode, tripcount, cal_passes=CAL_PASSES):
    return pipeline.per_pass_seconds(x, mode, tripcount,
                                     cal_passes=cal_passes)


def _fused_collective_detail() -> dict:
    """Fused-ring-collective headline keys (comm/fused.py), captured in
    the same measurement child as the overlap headline:

    - ``fused_allreduce_gbps``: ring-normalized bus bandwidth of
      ``Communicator.allreduce(algorithm="fused")`` — the
      device-initiated in-kernel ring;
    - ``allreduce_overlap_frac``: 1 - t(fused allgather_matmul) /
      t(host-driven gather-then-matmul), i.e. the fraction of the
      serial route's time the fused kernel hides by computing each
      matmul tile while the next shard's remote DMA is in flight
      (clamped at 0 — interpret mode serializes DMAs, so the CPU smoke
      legitimately measures no overlap);
    - ``allreduce_busbw_gbps``: the same busbw normalization measured
      on ``algorithm="collective"`` — the gated host-driven baseline
      row the fused number is judged against;
    - ``allreduce_gbps_by_algorithm``: the fused-vs-collective-vs-ring
      comparison row (informational, not gated).

    Returns {} on a single-device topology (no ring to run) or when
    the capture itself fails — the regression gate's coverage-loss
    check is what makes a silently vanished key visible.
    """
    import numpy as np

    from hpc_patterns_tpu import topology
    from hpc_patterns_tpu.comm import Communicator

    if len(jax.devices()) < 2:
        return {}
    on_tpu = jax.default_backend() == "tpu"
    # per-rank elements: the fused kernel keeps the whole shard + two
    # chunk-slot arrays VMEM-resident (no grid streaming yet), so the
    # chip shard is 4 MiB — wire-dominated but ~4x inside the kernel's
    # VMEM budget; the CPU smoke keeps the dma-discharge interpreter fast
    n = (1 << 20) if on_tpu else (1 << 11)
    reps = 10 if on_tpu else 3
    comm = Communicator(topology.make_mesh({"x": -1}), "x")
    x = comm.shard(np.ones((comm.size, n), np.float32))

    def best_seconds(fn, *args):
        jax.block_until_ready(fn(*args))  # compile + warm outside
        best = float("inf")
        for _ in range(reps):
            t0 = time.perf_counter()
            jax.block_until_ready(fn(*args))
            best = min(best, time.perf_counter() - t0)
        return best

    gbps = {}
    nbytes = n * x.dtype.itemsize
    for alg in ("fused", "collective", "ring_chunked"):
        t = best_seconds(comm.jit_allreduce(x, alg), x)
        # ring busbw normalization: 2*S*(size-1)/size bytes per link
        gbps[alg] = 2 * nbytes * (comm.size - 1) / comm.size / t / 1e9

    m, k, n_w = (256, 1024, 1024) if on_tpu else (4, 32, 16)
    xa = comm.shard(np.ones((comm.size, m, k), np.float32))
    w = comm.shard(np.ones((comm.size, k, n_w), np.float32))
    t_fused = best_seconds(
        lambda a, b: comm.allgather_matmul(a, b, "fused"), xa, w)
    t_host = best_seconds(
        lambda a, b: comm.allgather_matmul(a, b, "collective"), xa, w)
    return {
        "fused_allreduce_gbps": round(gbps["fused"], 3),
        # the gated host-driven baseline row: the same ring-busbw
        # normalization measured on algorithm="collective" (the
        # jax.lax.psum route the fused kernel is judged against)
        "allreduce_busbw_gbps": round(gbps["collective"], 3),
        "allreduce_overlap_frac": round(
            max(0.0, 1.0 - t_fused / t_host), 4) if t_host > 0 else 0.0,
        "allreduce_gbps_by_algorithm": {
            a: round(v, 3) for a, v in gbps.items()},
    }


def _serving_detail() -> dict:
    """Single-engine serving headline keys, captured in the same
    measurement child as the overlap headline:

    - ``serving_tok_s``: engine-window tok/s of the continuous
      batcher on ``bench_serving.run_bench``'s smoke shape
      (oracle-exact vs standalone decode before the number exists);
    - ``serving_bubble_frac``: host-gap fraction of that engine
      window — the overlapped-admission claim in one number;
    - ``serving_prefill_compiles``: distinct prefill compilations the
      bucket ladder admitted (a ladder regression shows up as a
      compile-count jump before it shows up in the wall clock).

    These three are the oldest gated keys in ``regress.py``'s table
    and were captured by hand (or not at all) until contractlint's
    ``gate-key-orphan`` flagged them as emitterless. Returns {} on
    failure — the gate's coverage-loss warning is the tripwire."""
    import sys

    sys.path.insert(0, os.path.join(
        os.path.dirname(os.path.abspath(__file__)), "benchmarks"))
    import bench_serving

    r = bench_serving.run_bench(**bench_serving.smoke_config(),
                                quiet=True)
    return {
        "serving_tok_s": round(r["tokens_per_s_engine"], 1),
        "serving_bubble_frac": round(r["bubble_frac"], 4),
        "serving_prefill_compiles": int(r["prefill_compiles"]),
    }


def _serving_plane_detail() -> dict:
    """Serving-plane headline keys (round 10), captured in the same
    measurement child as the overlap headline:

    - ``plane_goodput_tok_s``: SLO-attained tok/s of an open-loop
      stream routed across a homogeneous 2-replica plane;
    - ``kv_migration_overlap_frac``: the measured fraction of each
      KV-handoff window hidden under the destination replica's
      in-flight decode chunk in the disaggregated 1-prefill/1-decode
      shape (serving_plane/router.py);
    - ``dma_migration_overlap_frac`` / ``migration_bytes_per_round``
      (round 17): the same overlap measured on a second 1p/1d run
      whose handoffs ride the fused paired remote-DMA kernel
      (``ServingPlane(migration="dma")``, comm/migration_dma.py) —
      the router reports the DMA ledger only for bundles that
      actually rode the kernel, so a silent fallback shows up as
      coverage loss here, not as a wrong number — and the dispatched
      KV-payload bytes per plane round on that run.

    Runs ``bench_serving.run_plane``'s smoke shape (oracle-exact on
    every leg before any number is returned). Returns {} when there is
    nothing to run on; a failed capture surfaces through the gate's
    coverage-loss warning."""
    import sys

    sys.path.insert(0, os.path.join(
        os.path.dirname(os.path.abspath(__file__)), "benchmarks"))
    import bench_serving

    r = bench_serving.run_plane(**bench_serving.plane_smoke_config(),
                                quiet=True)
    rd = bench_serving.run_plane(**bench_serving.plane_smoke_config(),
                                 migration="dma", quiet=True)
    detail = {
        "plane_goodput_tok_s": round(r["plane_goodput_tok_s"], 1),
        "kv_migration_overlap_frac": round(
            r["kv_migration_overlap_frac"], 4),
        "plane_migrations": r["migrations"],
        "migration_bytes_per_round": round(
            rd["migration_bytes_per_round"], 1),
    }
    if rd["dma_migration_overlap_frac"] is not None:
        detail["dma_migration_overlap_frac"] = round(
            rd["dma_migration_overlap_frac"], 4)
    return detail


def _offload_detail() -> dict:
    """Tiered-memory headline keys (round 11), captured in the same
    measurement child as the overlap headline:

    - ``offload_goodput_tok_s``: SLO-attained tok/s of an engine whose
      HBM pool is capped well below the stream's working set, fronting
      a host-resident pool through the residency manager
      (``hpc_patterns_tpu/memory/``) — token-identical to the all-HBM
      engine before the number exists;
    - ``prefetch_overlap_frac``: measured fraction of host->HBM
      prefetch-window time hidden under the in-flight decode chunk
      (the stream-aware offloaded-messaging claim, proved from trace
      windows).

    Runs ``bench_serving.run_offload``'s smoke shape (oracle-exact,
    real eviction asserted). Returns {} on failure — the gate's
    coverage-loss warning is the tripwire for a vanished key."""
    import sys

    sys.path.insert(0, os.path.join(
        os.path.dirname(os.path.abspath(__file__)), "benchmarks"))
    import bench_serving

    r = bench_serving.run_offload(**bench_serving.offload_smoke_config(),
                                  quiet=True)
    return {
        "offload_goodput_tok_s": round(r["offload_goodput_tok_s"], 1),
        "prefetch_overlap_frac": round(r["prefetch_overlap_frac"], 4),
        "offload_swaps": r["swap_outs"],
    }


def _shared_prefix_detail() -> dict:
    """Prefix-sharing headline keys (round 12), captured in the same
    measurement child as the overlap headline:

    - ``shared_goodput_tok_s``: SLO-attained tok/s of a shared-prefix
      open-loop stream (template pool + conversation-tree turns)
      through the sharing-aware arena (``prefix_cache=True`` — radix
      match at admission, refcounted read-only page mapping, tail-only
      prefill), token-identical to a private-pages engine before the
      number exists;
    - ``prefill_skip_frac``: the fraction of submitted prompt tokens
      whose prefill the radix match skipped (asserted > 0.3 on the
      template mix inside the run).

    Runs ``bench_serving.run_shared``'s smoke shape. Returns {} on
    failure — the gate's coverage-loss warning is the tripwire."""
    import sys

    sys.path.insert(0, os.path.join(
        os.path.dirname(os.path.abspath(__file__)), "benchmarks"))
    import bench_serving

    r = bench_serving.run_shared(**bench_serving.shared_smoke_config(),
                                 quiet=True)
    return {
        "shared_goodput_tok_s": round(r["shared_goodput_tok_s"], 1),
        "prefill_skip_frac": round(r["prefill_skip_frac"], 4),
        "prefix_hits": r["prefix_hits"],
    }


def _elastic_detail() -> dict:
    """Elastic-plane headline keys (round 14), captured in the same
    measurement child as the overlap headline:

    - ``elastic_slo_attainment``: per-class SLO attainment of the
      autoscaled plane on a diurnal ramp under replica-death chaos —
      asserted STRICTLY above the fixed plane's on the same replayed
      schedule before the number exists (the fixed plane sheds);
    - ``goodput_per_replica_round``: SLO-attained tokens per live
      replica-round — the efficiency headline that rewards holding
      the SLO with fewer replica-rounds, not just holding it.

    Runs ``bench_serving.run_elastic``'s smoke shape (every served
    stream byte-exact greedy AND sampled, warm spin-up beat cold init,
    the death fault verified fired — all asserted inside). Returns {}
    on failure — the gate's coverage-loss warning is the tripwire."""
    import sys

    sys.path.insert(0, os.path.join(
        os.path.dirname(os.path.abspath(__file__)), "benchmarks"))
    import bench_serving

    r = bench_serving.run_elastic(
        **bench_serving.elastic_smoke_config(), quiet=True)
    return {
        "elastic_slo_attainment": round(r["elastic_slo_attainment"], 4),
        "goodput_per_replica_round": round(
            r["goodput_per_replica_round"], 2),
        "elastic_spinups": r["spinups"],
        "warm_spinup_ms": round(r["warm_spinup_s"] * 1e3, 2),
        "cold_init_ms": round(r["cold_init_s"] * 1e3, 2),
    }


def _autofit_detail() -> dict:
    """Autofit headline keys (round 16), captured in the same
    measurement child as the overlap headline:

    - ``fitted_goodput_tok_s``: tok/s of an engine built by
      ``ContinuousBatcher.from_fitted`` from a FittedConfig that
      ``harness/autofit.py`` fitted off the recording leg's own RunLog
      JSONL — the observability-becomes-control loop closed end to
      end;
    - ``autofit_gain_frac``: fitted over default wall clock minus one
      on the same stream and pool geometry (the fitted ladder's
      expected padding is asserted STRICTLY below the default's before
      either number exists).

    Runs ``bench_serving.run_fitted``'s smoke shape (both legs
    byte-exact vs standalone decode). Returns {} on failure — the
    gate's coverage-loss warning is the tripwire."""
    import sys

    sys.path.insert(0, os.path.join(
        os.path.dirname(os.path.abspath(__file__)), "benchmarks"))
    import bench_serving

    r = bench_serving.run_fitted(**bench_serving.fit_smoke_config(),
                                 quiet=True)
    return {
        "fitted_goodput_tok_s": round(r["fitted_goodput_tok_s"], 1),
        "autofit_gain_frac": round(r["autofit_gain_frac"], 4),
        "autofit_padding_default": round(
            r["expected_padding_default"], 2),
        "autofit_padding_fitted": round(r["expected_padding_fitted"], 2),
    }


def _reqtrace_detail() -> dict:
    """Request-forensics headline keys (round 18), captured in the
    same measurement child as the overlap headline:

    - ``attribution_coverage_frac``: fraction of finished-request wall
      time the lifecycle-segment tilings (harness/reqtrace.py) account
      for over the chaos scenario's timed leg — run_scenario already
      asserts it in-run at >= 0.95, so the gate watches for drift, not
      correctness;
    - ``ttft_p99_queue_share``: share of the p99 TTFT band's
      attribution window spent in the ``queued`` segment
      (harness/explain.py) — the "where did the p99 go" number,
      captured per round so tail regressions come pre-attributed.

    The same scenario run also yields the robustness row's gated
    keys — ``serving_goodput_tok_s`` (SLO-attained tok/s under
    chaos) and ``serving_degraded_bubble_frac`` (the degraded-mode
    engine bubble) — which had no emitter at all until contractlint's
    ``gate-key-orphan`` flagged the orphaned gate rows.

    Runs ``bench_serving.run_scenario``'s smoke shape (oracle-exact,
    chaos seeded). Returns {} on failure — the gate's coverage-loss
    warning is the tripwire."""
    import sys

    sys.path.insert(0, os.path.join(
        os.path.dirname(os.path.abspath(__file__)), "benchmarks"))
    import bench_serving

    r = bench_serving.run_scenario(
        **bench_serving.scenario_smoke_config(), quiet=True)
    return {
        "attribution_coverage_frac": round(
            r["attribution_coverage_frac"], 4),
        "ttft_p99_queue_share": round(r["ttft_p99_queue_share"], 4),
        "serving_goodput_tok_s": round(r["goodput_tok_s"], 1),
        "serving_degraded_bubble_frac": round(r["bubble_frac"], 4),
    }


def _budget_detail() -> dict:
    """Segment-budget headline keys (round 20), the attribution
    loop's gate feed:

    - ``tpot_p99_stall_share``: share of the pooled p99 inter-token
      gap band spent in decode-stall segments
      (harness/explain.py TPOT_STALL_KINDS) over the seeded
      slow_host_transfer row — the "where did the inter-token tail
      go" number;
    - ``budget_breach_segments``: how many distinct segments breached
      their SLO-budget allowance (harness/budget.py) — run_slo_budget
      already asserts the set is exactly {"prefetch_wait"} in-run, so
      the gate watches the count for smear (a second breached segment
      means attribution leaked out of the injected mechanism).

    Runs ``bench_serving.run_slo_budget``'s one shape (oracle-exact,
    chaos seeded, breach set asserted inside). Returns {} on failure
    — the gate's coverage-loss warning is the tripwire."""
    import sys

    sys.path.insert(0, os.path.join(
        os.path.dirname(os.path.abspath(__file__)), "benchmarks"))
    import bench_serving

    r = bench_serving.run_slo_budget(
        **bench_serving.slo_budget_smoke_config(), quiet=True)
    return {
        "tpot_p99_stall_share": round(r["tpot_p99_stall_share"], 4),
        "budget_breach_segments": len(r["budget_breach_segments"]),
    }


def _quantized_detail() -> dict:
    """Quantized-decode headline keys (round 13), captured in the same
    measurement child as the overlap headline:

    - ``quant_goodput_tok_s``: SLO-attained tok/s of an engine serving
      from an int8 KV pool (one-byte pages + per-row scales), gated
      only after BOTH oracles pass — token-identical to standalone
      decode within the precision, and the teacher-forced precision
      law (greedy top-1 agreement + TV-distance bounds,
      models/quantization.py) against the baseline precision;
    - ``kv_pool_bytes_frac``: measured quantized-pool bytes over a
      bf16 pool at equal residents (~0.53 — the capacity multiplier
      every tier inherits);
    - ``quant_bubble_frac``: the quantized engine's admission-bubble
      fraction (the per-precision bubble % the gate watches).

    Runs ``bench_serving.run_quantized``'s smoke shape. Returns {} on
    failure — the gate's coverage-loss warning is the tripwire."""
    import sys

    sys.path.insert(0, os.path.join(
        os.path.dirname(os.path.abspath(__file__)), "benchmarks"))
    import bench_serving

    r = bench_serving.run_quantized(
        **bench_serving.quantized_smoke_config(), quiet=True)
    return {
        "quant_goodput_tok_s": round(r["quant_goodput_tok_s"], 1),
        "kv_pool_bytes_frac": round(r["kv_pool_bytes_frac"], 4),
        "quant_bubble_frac": round(r["quant_bubble_frac"], 4),
    }


def _unavailable_line(err: BaseException) -> str:
    """Degenerate-capture verdict line for a backend that won't even
    initialize (value 0.0, never a pass, the error preserved)."""
    return json.dumps(
        {
            "metric": "onchip_overlap_speedup",
            "value": 0.0,
            "unit": "x",
            "vs_baseline": 0.0,
            "detail": {
                "degenerate": True,
                "backend": "unavailable",
                "error": f"{type(err).__name__}: {err}",
            },
        }
    )


def _emit_unavailable(err: BaseException) -> int:
    """Degenerate capture for a backend that won't even initialize.

    The reference's binaries emit a machine-readable verdict in every
    failure mode (concurency/sycl_con.cpp:279-296); BENCH_r04 died rc=1
    with a traceback because the round-4 chip session degraded until
    `jax.default_backend()` itself raised. This path makes that failure
    a self-describing artifact: value 0.0, never a pass, backend
    "unavailable", the error preserved in detail.
    """
    print(
        _unavailable_line(err),
        flush=True,  # must reach the pipe before any teardown hang
    )
    return 0


def _supervise() -> int:
    """Print the supervised capture's one verdict line; always rc 0
    (the verdict itself carries failure as a degenerate capture)."""
    print(_supervised_capture())
    return 0


def _supervised_capture() -> str:
    """Run the measurement in a child process, enforcing timeouts from
    outside — the only guard that works when jax-import/backend-attach
    blocks inside the plugin's C code. ``HPCPAT_BENCH_INIT_TIMEOUT``
    (default 600 s) bounds import+attach; ``HPCPAT_BENCH_TOTAL_TIMEOUT``
    (default 3600 s) bounds the whole capture — round 4's session died
    MID-measurement, so both phases need a deadline. 0 disables either.
    Returns the one JSON verdict line (a degenerate ``_unavailable_line``
    when the child hung or died with no capture).
    """
    init_t = int(os.environ.get("HPCPAT_BENCH_INIT_TIMEOUT", "600"))
    total_t = int(os.environ.get("HPCPAT_BENCH_TOTAL_TIMEOUT", "3600"))
    env = dict(os.environ, HPCPAT_BENCH_CHILD="1")
    proc = subprocess.Popen(
        [sys.executable, os.path.abspath(__file__)],
        stdout=subprocess.PIPE, env=env,
    )
    # Raw-fd reads with our own line buffer: select() on the fd plus a
    # buffered readline() can block while a complete line already sits
    # in the text-layer buffer.
    fd = proc.stdout.fileno()
    start = time.monotonic()
    got_up = False
    json_line = None
    buf = b""
    timed_out = None

    def _consume(chunk):
        nonlocal buf, got_up, json_line
        buf += chunk
        while b"\n" in buf:
            line, buf = buf.split(b"\n", 1)
            line = line.strip().decode("utf-8", "replace")
            if line == _UP_SENTINEL:
                got_up = True
            elif line:
                try:  # only a parseable verdict counts as the capture
                    json.loads(line)
                except ValueError:
                    continue
                json_line = line

    try:
        while True:
            deadlines = []
            if total_t > 0:
                deadlines.append(start + total_t)
            if not got_up and init_t > 0:
                deadlines.append(start + init_t)
            timeout = (max(0.0, min(deadlines) - time.monotonic())
                       if deadlines else None)
            r, _, _ = select.select([fd], [], [], timeout)
            if not r:
                phase = ("jax import / backend init" if not got_up
                         else "measurement")
                limit = init_t if not got_up else total_t
                timed_out = TimeoutError(
                    f"{phase} exceeded {limit}s (chip session "
                    "unresponsive)")
                break
            chunk = os.read(fd, 65536)
            if not chunk:
                break  # child EOF
            _consume(chunk)
            if json_line is not None:
                # verdict in hand — don't wait out a teardown hang
                break
    finally:
        if proc.poll() is None:
            proc.kill()
    proc.wait()
    # drain anything the child managed to write before dying/being
    # killed — a capture that finished just before a teardown hang must
    # win over the timeout verdict. Non-blocking: a plugin helper
    # process inheriting the pipe's write end could otherwise hold this
    # read open forever.
    try:
        os.set_blocking(fd, False)
        while True:
            chunk = os.read(fd, 65536)
            if not chunk:
                break
            _consume(chunk)
    except (BlockingIOError, OSError, ValueError):
        pass
    if json_line is not None:
        return json_line
    if timed_out is not None:
        return _unavailable_line(timed_out)
    return _unavailable_line(
        RuntimeError(f"measurement child exited rc={proc.returncode} "
                     "with no capture"))


def _run_gate(argv) -> int:
    """``bench.py --gate``: capture a new round, write it as the next
    ``BENCH_rNN.json``, then run the regression gate
    (hpc_patterns_tpu.harness.regress) over the whole trajectory and
    exit with ITS status — so a re-grounding sequence fails loudly when
    the newest measured round degrades a headline metric.

    The gate subprocess runs with ``JAX_PLATFORMS=cpu``: regress itself
    is pure JSON math, but importing the package initializes jax, and
    this supervisor must never touch the chip tunnel (a dead tunnel
    hangs ``import jax`` in C — the whole reason the supervisor
    exists).
    """
    import argparse
    import glob

    p = argparse.ArgumentParser(
        description="bench capture + regression gate")
    p.add_argument("--gate", action="store_true")
    p.add_argument("--rounds-glob", default="BENCH_r*.json",
                   help="trajectory files to gate against")
    p.add_argument("--out", default=None,
                   help="round file to write (default: next BENCH_rNN)")
    p.add_argument("--tolerance", type=float, default=None,
                   help="passed through to harness.regress")
    args = p.parse_args(argv)

    line = _supervised_capture()
    print(line, flush=True)
    try:
        parsed = json.loads(line)
    except ValueError:
        parsed = None
    here = os.path.dirname(os.path.abspath(__file__))
    prior = sorted(glob.glob(os.path.join(here, args.rounds_glob)))
    n = 0
    for path in prior:
        try:
            with open(path) as f:
                n = max(n, int(json.load(f).get("n", 0)))
        except (OSError, ValueError):
            continue
    n += 1
    # absolute: the gate subprocess runs with cwd=here, so a relative
    # --out from another cwd would otherwise point it at the wrong file
    out = os.path.abspath(args.out) if args.out else os.path.join(
        here, f"BENCH_r{n:02d}.json")
    with open(out, "w") as f:
        json.dump({"n": n, "cmd": "python bench.py --gate",
                   "rc": 0 if parsed is not None else 1,
                   "tail": line + "\n", "parsed": parsed}, f, indent=2)
    print(f"wrote round {n} -> {out}", flush=True)
    cmd = [sys.executable, "-m", "hpc_patterns_tpu.harness.regress",
           *prior, out]
    if args.tolerance is not None:
        cmd += ["--tolerance", str(args.tolerance)]
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    try:
        return subprocess.run(cmd, env=env, cwd=here,
                              timeout=300).returncode
    except subprocess.TimeoutExpired:
        print("ERROR: regression gate timed out", flush=True)
        return 1


def main() -> int:
    # Supervised by default; HPCPAT_BENCH_CHILD marks the measurement
    # child, HPCPAT_BENCH_SUPERVISE=0 opts out (e.g. under a debugger).
    if os.environ.get("HPCPAT_BENCH_CHILD") != "1" and "--gate" in sys.argv:
        return _run_gate(sys.argv[1:])
    if (os.environ.get("HPCPAT_BENCH_CHILD") != "1"
            and os.environ.get("HPCPAT_BENCH_SUPERVISE", "1") != "0"):
        return _supervise()

    # Belt-and-braces in-process watchdog for raise-style failures and
    # pure-Python hangs (covers the unsupervised mode too).
    global jax, pipeline
    init_timeout = int(os.environ.get("HPCPAT_BENCH_INIT_TIMEOUT", "600"))

    def _alarm(signum, frame):
        raise TimeoutError(
            f"jax import / backend init exceeded {init_timeout}s "
            "(tunnel unresponsive)"
        )

    try:
        if init_timeout > 0 and hasattr(signal, "SIGALRM"):
            signal.signal(signal.SIGALRM, _alarm)
            signal.alarm(init_timeout)
        # the fused-collective row needs a ring: give the CPU fallback
        # the suite's 8-device virtual mesh (host-platform only — a TPU
        # backend ignores it)
        flags = os.environ.get("XLA_FLAGS", "")
        if "xla_force_host_platform_device_count" not in flags:
            os.environ["XLA_FLAGS"] = (
                flags + " --xla_force_host_platform_device_count=8"
            ).strip()
        import jax
        from hpc_patterns_tpu.concurrency import pipeline
        on_tpu = jax.default_backend() == "tpu"
    except Exception as err:  # init failure or hang — emit, don't crash
        return _emit_unavailable(err)
    finally:
        if init_timeout > 0 and hasattr(signal, "SIGALRM"):
            signal.alarm(0)
    # tell the supervisor the init phase is over — only when one is
    # listening (unsupervised stdout must stay a single JSON line)
    if os.environ.get("HPCPAT_BENCH_CHILD") == "1":
        print(_UP_SENTINEL, flush=True)
    # CPU fallback (no real DMA engine): tiny shapes through the
    # interpreter so the protocol still runs end-to-end.
    num_chunks, chunk_rows = (NUM_CHUNKS, CHUNK_ROWS) if on_tpu else (4, 8)
    cal = CAL_PASSES if on_tpu else 2

    measure_error = None
    try:
        x = jax.block_until_ready(
            pipeline.make_hbm_array(num_chunks, chunk_rows))
        t_dma = per_pass_seconds(x, "dma", PROBE_TRIPS, cal)
        t_comp_probe = per_pass_seconds(x, "compute", PROBE_TRIPS, cal)
    except Exception as err:  # session died mid-measurement
        measure_error = err
        t_dma = t_comp_probe = 0.0
        x = None
    if t_dma <= 0 or t_comp_probe <= 0:
        # probe measured nothing usable — don't autotune into a
        # pathological tripcount; fall through to the degenerate emitter
        trips, t_comp, t_serial, t_overlap = 0, 0.0, 0.0, 0.0
        raw_pairs = []
    else:
        try:
            # balance compute to DMA (the shared C12 balance step)
            trips = min(max(1, int(PROBE_TRIPS * t_dma / t_comp_probe)),
                        MAX_TRIPS)
            trips, t_comp = pipeline.balance_tripcount(
                lambda m, t: per_pass_seconds(x, m, t, cal), t_dma,
                "compute", trips, max_trips=MAX_TRIPS,
            )

            # five (serial, overlap) pairs measured back to back, MEDIAN
            # ratio wins: chip/tunnel conditions drift run to run, so the
            # two legs of a ratio must be temporally adjacent or the
            # speedup wobbles by several percent — and the median (unlike
            # a max-of-ratios) cannot be inflated by a lucky noise draw
            pairs = [
                p for p in (
                    (per_pass_seconds(x, "serial", trips, cal),
                     per_pass_seconds(x, "overlap", trips, cal))
                    for _ in range(5)
                ) if min(p) > 0
            ]
            raw_pairs = list(pairs)
            if pairs:
                pairs = sorted(pairs, key=lambda p: p[0] / p[1])
                t_serial, t_overlap = pairs[len(pairs) // 2]
            else:
                t_serial = t_overlap = 0.0
        except Exception as err:  # session died mid-measurement
            measure_error = err
            trips, t_comp, t_serial, t_overlap = 0, 0.0, 0.0, 0.0
            raw_pairs = []

    # the fused-ring-collective row (device-initiated allreduce +
    # overlapped allgather-matmul); a failed capture yields {} and the
    # gate's coverage-loss warning is the tripwire for its absence
    try:
        fused_detail = _fused_collective_detail()
    except Exception as err:  # noqa: BLE001 — never sink the headline
        fused_detail = {"fused_collective_error":
                        f"{type(err).__name__}: {err}"}

    # the single-engine serving row: continuous-batcher tok/s, engine
    # bubble fraction, and the ladder's prefill-compile count
    # (bench_serving.run_bench smoke — oracle-exact before any number
    # is returned)
    try:
        serving_detail = _serving_detail()
    except Exception as err:  # noqa: BLE001 — never sink the headline
        serving_detail = {"serving_error":
                          f"{type(err).__name__}: {err}"}

    # the serving-plane row (round 10): router goodput across 2
    # replicas + the KV-migration overlap fraction of the
    # disaggregated 1p/1d shape (bench_serving.run_plane smoke —
    # oracle-exact before either number exists)
    try:
        plane_detail = _serving_plane_detail()
    except Exception as err:  # noqa: BLE001 — never sink the headline
        plane_detail = {"serving_plane_error":
                        f"{type(err).__name__}: {err}"}

    # the tiered-memory row (round 11): constrained-HBM goodput + the
    # measured prefetch-under-chunk overlap (bench_serving.run_offload
    # smoke — token-identical to all-HBM, real eviction asserted)
    try:
        offload_detail = _offload_detail()
    except Exception as err:  # noqa: BLE001 — never sink the headline
        offload_detail = {"offload_error":
                          f"{type(err).__name__}: {err}"}

    # the prefix-sharing row (round 12): sharing-arena goodput on a
    # template/conversation-tree stream + the measured prefill-skip
    # fraction (bench_serving.run_shared smoke — token-identical to
    # private pages before either number exists)
    try:
        shared_detail = _shared_prefix_detail()
    except Exception as err:  # noqa: BLE001 — never sink the headline
        shared_detail = {"shared_prefix_error":
                         f"{type(err).__name__}: {err}"}

    # the quantized-decode row (round 13): int8-KV goodput + the
    # pool-bytes fraction vs bf16 (bench_serving.run_quantized smoke —
    # both precision oracles pass before either number exists)
    try:
        quant_detail = _quantized_detail()
    except Exception as err:  # noqa: BLE001 — never sink the headline
        quant_detail = {"quantized_error":
                        f"{type(err).__name__}: {err}"}

    # the elastic-plane row (round 14): autoscaled-vs-static SLO
    # attainment under replica-death chaos + goodput per replica-round
    # (bench_serving.run_elastic smoke — byte-exact greedy AND
    # sampled, warm spin-up beat cold init, all asserted inside)
    try:
        elastic_detail = _elastic_detail()
    except Exception as err:  # noqa: BLE001 — never sink the headline
        elastic_detail = {"elastic_error":
                          f"{type(err).__name__}: {err}"}

    # the autofit row (round 16): profile-fitted config A/B — the
    # fitted ladder's strict padding win + the measured wall-clock
    # gain (bench_serving.run_fitted smoke — fit ingested through the
    # real RunLog -> autofit -> from_fitted path, oracle-exact)
    try:
        autofit_detail = _autofit_detail()
    except Exception as err:  # noqa: BLE001 — never sink the headline
        autofit_detail = {"autofit_error":
                          f"{type(err).__name__}: {err}"}

    # the request-forensics row (round 18): lifecycle-segment coverage
    # + the p99 band's queued share over the chaos scenario smoke
    # (bench_serving.run_scenario — coverage invariant asserted
    # in-run before either number exists)
    try:
        reqtrace_detail = _reqtrace_detail()
    except Exception as err:  # noqa: BLE001 — never sink the headline
        reqtrace_detail = {"reqtrace_error":
                           f"{type(err).__name__}: {err}"}

    # the segment-budget row (round 20): the seeded decode-stall
    # stream's inter-token tail share + breached-segment count
    # (bench_serving.run_slo_budget — breach set pinned to the
    # injected mechanism in-run before either number exists)
    try:
        budget_detail = _budget_detail()
    except Exception as err:  # noqa: BLE001 — never sink the headline
        budget_detail = {"budget_error":
                         f"{type(err).__name__}: {err}"}

    # any clamped-to-zero component means the run measured nothing usable
    degenerate = min(t_overlap, t_serial, t_dma, t_comp) <= 0
    if degenerate:
        # report "measured nothing", never a pass
        speedup, theoretical, vs_baseline = 0.0, 0.0, 0.0
    else:
        speedup = t_serial / t_overlap
        theoretical = (t_dma + t_comp) / max(t_dma, t_comp, 1e-12)
        vs_baseline = speedup / (theoretical / 1.3) if theoretical > 0 else 0.0
    nbytes = x.size * 4 if x is not None else 0
    print(
        json.dumps(
            {
                "metric": "onchip_overlap_speedup",
                "value": round(speedup, 4),
                "unit": "x",
                "vs_baseline": round(vs_baseline, 4),
                "detail": {
                    "t_dma_us": round(t_dma * 1e6, 2),
                    "t_compute_us": round(t_comp * 1e6, 2),
                    "t_serial_us": round(t_serial * 1e6, 2),
                    "t_overlap_us": round(t_overlap * 1e6, 2),
                    "dma_gbps": round(nbytes / t_dma / 1e9, 1) if t_dma > 0 else None,
                    "theoretical_max_speedup": round(theoretical, 4),
                    "tripcount": trips,
                    "degenerate": degenerate,
                    "error": (f"{type(measure_error).__name__}: "
                              f"{measure_error}")
                    if measure_error is not None else None,
                    "backend": jax.default_backend(),
                    **fused_detail,
                    **serving_detail,
                    **plane_detail,
                    **offload_detail,
                    **shared_detail,
                    **quant_detail,
                    **elastic_detail,
                    **autofit_detail,
                    **reqtrace_detail,
                    **budget_detail,
                    # the five raw (serial, overlap) pairs, measurement
                    # order — the distribution behind the median
                    "pairs_us": [
                        [round(s * 1e6, 2), round(o * 1e6, 2)]
                        for s, o in raw_pairs
                    ],
                    "session": {
                        "dma_gbps_nominal": NOMINAL_DMA_GBPS,
                        # only meaningful against the TPU nominal rate
                        "slow": bool(
                            on_tpu
                            and t_dma > 0
                            and nbytes / t_dma / 1e9
                            < 0.9 * NOMINAL_DMA_GBPS
                        ),
                    },
                },
            }
        ),
        # the supervisor's drain only sees what reached the pipe: an
        # unflushed verdict dies with the child on a teardown hang
        flush=True,
    )
    return 0


if __name__ == "__main__":
    sys.exit(main())
