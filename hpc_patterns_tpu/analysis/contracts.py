"""contractlint extraction: whole-tree producer/consumer tables.

The repo's cross-module seams are stringly typed by design — metric
names (``harness/metrics.py``), RunLog record kinds
(``harness/runlog.py``), bench gate keys (``harness/regress.py``
``SPECS`` vs. the ``detail`` dicts ``bench.py`` emits), the migration
wire codec's field names (``serving_plane/migration.py``), Perfetto
device-subtrack bands (``harness/trace.py`` ``TRACK_BANDS``), and
chaos site/kind names (``harness/chaos.py``). Every one of them is a
producer/consumer contract that Python cannot check, and the review
pass of PRs 5/9/16/17/18 caught drift in each BY HAND.

This module is the first pass of the contractlint family
(``contract_rules.py``): pure stdlib ``ast`` extraction of the
producer and consumer tables, per module, merged over a TREE. The
rules (second pass) judge a module's own sites against the merged
tables, so a deleted emitter becomes a finding at the surviving
consumer's line — review-time, not a runtime coverage-loss warning.

Tree resolution (``tables_for``): a module under the live repo (an
ancestor directory holding both ``bench.py`` and the
``hpc_patterns_tpu`` package) is judged against tables merged over
the whole repo — package + ``bench.py`` + ``benchmarks/`` +
``tests/`` (fixture corpora excluded). A module under a ``fixtures``
directory — or outside any repo root — is judged SELF-CONTAINED: its
own file is the whole tree, which is what makes the bad/clean fixture
twins reproducible without dragging the live tables in.

Like the rest of the analyzer, nothing here imports the code under
analysis; the live-tree tables are cached per root for the process
lifetime (the tree does not change under a single analyzer run).
"""

from __future__ import annotations

import ast
import re
from dataclasses import dataclass, field
from pathlib import Path

from hpc_patterns_tpu.analysis.core import ModuleInfo, iter_python_files

#: chaos spec literals look like "kind:key=val,...;kind:..." — the
#: kind prefix is the contract half checked against chaos.KINDS
_CHAOS_SPEC_RE = re.compile(r"^[a-z_]+:[a-z_]+=")

#: calls whose first string argument claims a chaos SITE name
_CHAOS_SITE_FUNCS = frozenset(
    {"maybe_inject", "matching", "suppress", "record_injection"})

#: call keywords / function names that carry a chaos SPEC string
_CHAOS_SPEC_KWARGS = frozenset({"chaos_spec", "chaos", "spec"})
_CHAOS_SPEC_FUNCS = frozenset({"configure", "parse"})


@dataclass(frozen=True)
class Site:
    """One producer or consumer occurrence: where, and which name."""

    path: str
    line: int
    col: int
    name: str
    #: role-specific payload (e.g. the band range, the spec path)
    detail: str = ""


@dataclass(frozen=True)
class Band:
    """One device-subtrack band: ``[base, base + count)``."""

    name: str
    base: int
    count: int
    site: Site

    @property
    def hi(self) -> int:
        return self.base + self.count - 1

    def overlaps(self, other: "Band") -> bool:
        return (self.base <= other.hi and other.base <= self.hi)

    def covers(self, track: int) -> bool:
        return self.base <= track <= self.hi


@dataclass
class ContractTables:
    """The merged producer/consumer tables for one tree."""

    root: str = ""  # "" = self-contained single module
    files: tuple[str, ...] = ()
    # -- telemetry (metric names + device-window span names) --------
    gauges_produced: dict[str, list[Site]] = field(default_factory=dict)
    #: f-string producers ("plane.{name}.queue_depth") reduced to
    #: their literal prefix — consumers match by startswith
    gauge_prefixes: list[Site] = field(default_factory=list)
    gauges_consumed: list[Site] = field(default_factory=list)
    spans_produced: dict[str, list[Site]] = field(default_factory=dict)
    spans_consumed: list[Site] = field(default_factory=list)
    # -- bench gate keys --------------------------------------------
    #: every string key a bench-tree dict literal/store emits
    detail_keys: dict[str, list[Site]] = field(default_factory=dict)
    #: MetricSpec(...) paths consumed by the regression gate
    gate_specs: list[Site] = field(default_factory=list)
    # -- RunLog record kinds ----------------------------------------
    kinds_produced: dict[str, list[Site]] = field(default_factory=dict)
    kinds_consumed: dict[str, list[Site]] = field(default_factory=dict)
    #: FORENSIC_KINDS declarations: written for the record stream /
    #: replay tooling, deliberately never string-dispatched
    forensic_kinds: dict[str, Site] = field(default_factory=dict)
    # -- Perfetto device-subtrack bands -----------------------------
    #: TRACK_BANDS registry literal(s): name -> Band
    declared_bands: dict[str, Band] = field(default_factory=dict)
    #: track_band("<name>") references at module scope / call sites
    band_refs: list[Site] = field(default_factory=list)
    #: hand-written ``*_TRACK_BASE = <int>`` literals
    band_literals: list[Site] = field(default_factory=list)
    #: ``track=<int>`` literal call-site arguments
    track_literals: list[Site] = field(default_factory=list)
    # -- chaos ------------------------------------------------------
    chaos_kinds: dict[str, Site] = field(default_factory=dict)
    chaos_sites: dict[str, Site] = field(default_factory=dict)
    chaos_site_claims: list[Site] = field(default_factory=list)
    chaos_kind_claims: list[Site] = field(default_factory=list)

    def merge(self, other: "ContractTables") -> None:
        for name, sites in other.gauges_produced.items():
            self.gauges_produced.setdefault(name, []).extend(sites)
        self.gauge_prefixes.extend(other.gauge_prefixes)
        self.gauges_consumed.extend(other.gauges_consumed)
        for name, sites in other.spans_produced.items():
            self.spans_produced.setdefault(name, []).extend(sites)
        self.spans_consumed.extend(other.spans_consumed)
        for name, sites in other.detail_keys.items():
            self.detail_keys.setdefault(name, []).extend(sites)
        self.gate_specs.extend(other.gate_specs)
        for name, sites in other.kinds_produced.items():
            self.kinds_produced.setdefault(name, []).extend(sites)
        for name, sites in other.kinds_consumed.items():
            self.kinds_consumed.setdefault(name, []).extend(sites)
        self.forensic_kinds.update(other.forensic_kinds)
        self.declared_bands.update(other.declared_bands)
        self.band_refs.extend(other.band_refs)
        self.band_literals.extend(other.band_literals)
        self.track_literals.extend(other.track_literals)
        self.chaos_kinds.update(other.chaos_kinds)
        self.chaos_sites.update(other.chaos_sites)
        self.chaos_site_claims.extend(other.chaos_site_claims)
        self.chaos_kind_claims.extend(other.chaos_kind_claims)

    # -- lookups the rules share ------------------------------------

    def gauge_has_producer(self, name: str) -> bool:
        if name in self.gauges_produced:
            return True
        return any(name.startswith(p.name) for p in self.gauge_prefixes)

    def band_covering(self, track: int) -> Band | None:
        for band in self.declared_bands.values():
            if band.covers(track):
                return band
        return None


# ---------------------------------------------------------------------------
# per-module extraction
# ---------------------------------------------------------------------------


def _is_chaos_call(mod: ModuleInfo, fn: ast.AST) -> bool:
    resolved = (mod.resolve(fn) or "").lower()
    return ("chaos" in resolved
            or "chaos" in Path(mod.path).stem.lower())


def _site(path: str, node: ast.AST, name: str, detail: str = "") -> Site:
    return Site(path=path, line=getattr(node, "lineno", 1),
                col=getattr(node, "col_offset", 0), name=name,
                detail=detail)


def _last_segment(mod: ModuleInfo, node: ast.AST) -> str:
    return (mod.resolve(node) or "").rsplit(".", 1)[-1]


def _str_const(node: ast.AST) -> str | None:
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return node.value
    return None


def _int_const(node: ast.AST) -> int | None:
    if isinstance(node, ast.Constant) and isinstance(node.value, int) \
            and not isinstance(node.value, bool):
        return node.value
    return None


def _module_str_constants(mod: ModuleInfo) -> dict[str, str]:
    """Top-level ``NAME = "literal"`` assignments — both sides of a
    kind contract may spell the kind through one (``FITTED_KIND``,
    ``ROLLUP_KIND``), and the extraction must see through it."""
    out: dict[str, str] = {}
    for node in mod.tree.body:
        if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                and isinstance(node.targets[0], ast.Name):
            value = _str_const(node.value)
            if value is not None:
                out[node.targets[0].id] = value
    return out


def _reads_kind_field(node: ast.AST) -> bool:
    """``rec["kind"]`` or ``rec.get("kind", ...)``."""
    if isinstance(node, ast.Subscript):
        return _str_const(node.slice) == "kind"
    if isinstance(node, ast.Call) and isinstance(node.func, ast.Attribute):
        return (node.func.attr == "get" and node.args
                and _str_const(node.args[0]) == "kind")
    return False


def _kind_vars(tree: ast.Module) -> set[str]:
    """Names bound from a record's kind field (``kind =
    rec.get("kind", "?")``) — ONLY such names count as kind reads
    when compared bare, so the many other ``kind`` locals in the tree
    (chaos fault kinds, CLI command kinds, lifecycle-segment kinds)
    never register as record-kind consumers."""
    out: set[str] = set()
    for node in ast.walk(tree):
        if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                and isinstance(node.targets[0], ast.Name) \
                and _reads_kind_field(node.value):
            out.add(node.targets[0].id)
    return out


def _kind_expr(node: ast.AST, kind_vars: set[str]) -> bool:
    """Does this expression read a record's ``kind``? Covers the
    repo's three consumer spellings: ``rec["kind"]``,
    ``rec.get("kind", ...)``, and a variable bound from either."""
    if _reads_kind_field(node):
        return True
    return isinstance(node, ast.Name) and node.id in kind_vars


def _str_tuple_elems(node: ast.AST) -> list[ast.Constant] | None:
    if isinstance(node, (ast.Tuple, ast.List, ast.Set)) and all(
            _str_const(e) is not None for e in node.elts):
        return list(node.elts)  # type: ignore[return-value]
    return None


def extract_module(mod: ModuleInfo,
                   bench_producer: bool = True) -> ContractTables:
    """One module's contract sites. ``bench_producer`` gates the
    detail-key harvest: in a live tree only ``bench.py`` /
    ``benchmarks/`` dict keys count as gate-key emitters (a test
    fabricating a round must not satisfy the gate table); a
    self-contained fixture is its own bench."""
    t = ContractTables()
    path = mod.path
    consts = _module_str_constants(mod)
    kind_vars = _kind_vars(mod.tree)

    def const_or_name(node: ast.AST) -> str | None:
        s = _str_const(node)
        if s is not None:
            return s
        if isinstance(node, ast.Name):
            return consts.get(node.id)
        return None

    # ---- module-level declarations (plain or annotated assigns) ----
    for node in mod.tree.body:
        if isinstance(node, ast.Assign) and len(node.targets) == 1:
            target = node.targets[0]
        elif isinstance(node, ast.AnnAssign) and node.value is not None:
            target = node.target
        else:
            continue
        # TRACK_BANDS = {"name": (base, count), ...}
        if isinstance(target, ast.Name) and target.id == "TRACK_BANDS" \
                and isinstance(node.value, ast.Dict):
            for k, v in zip(node.value.keys, node.value.values):
                name = _str_const(k) if k is not None else None
                if name is None or not isinstance(v, ast.Tuple) \
                        or len(v.elts) != 2:
                    continue
                base, count = (_int_const(v.elts[0]),
                               _int_const(v.elts[1]))
                if base is None or count is None:
                    continue
                t.declared_bands[name] = Band(
                    name=name, base=base, count=count,
                    site=_site(path, v, name, f"{base}..+{count}"))
        # FORENSIC_KINDS = ("...",)
        elif isinstance(target, ast.Name) \
                and target.id == "FORENSIC_KINDS":
            for e in _str_tuple_elems(node.value) or ():
                t.forensic_kinds[e.value] = _site(path, e, e.value)
        # chaos KINDS / SITES declarations (harness/chaos.py shape)
        elif isinstance(target, ast.Name) and target.id == "KINDS":
            for e in _str_tuple_elems(node.value) or ():
                t.chaos_kinds[e.value] = _site(path, e, e.value)
        elif isinstance(target, ast.Name) and target.id == "SITES":
            for e in _str_tuple_elems(node.value) or ():
                t.chaos_sites[e.value] = _site(path, e, e.value)
        # _DEFAULT_SITE = {"kind": "site"} — claims BOTH halves
        elif isinstance(target, ast.Name) \
                and target.id == "_DEFAULT_SITE" \
                and isinstance(node.value, ast.Dict):
            for k, v in zip(node.value.keys, node.value.values):
                if k is not None and _str_const(k) is not None:
                    t.chaos_kind_claims.append(
                        _site(path, k, _str_const(k), "default-site key"))
                if _str_const(v) is not None:
                    t.chaos_site_claims.append(
                        _site(path, v, _str_const(v),
                              "default-site value"))
        # hand-written band base: FOO_TRACK_BASE = <int>
        elif isinstance(target, ast.Name) \
                and target.id.endswith("_TRACK_BASE"):
            base = _int_const(node.value)
            if base is not None:
                t.band_literals.append(
                    _site(path, node.value, target.id, str(base)))

    # ---- whole-tree walk ------------------------------------------
    for node in ast.walk(mod.tree):
        # dict literals: bench detail keys + "kind": producers
        if isinstance(node, ast.Dict):
            for k, v in zip(node.keys, node.values):
                key = _str_const(k) if k is not None else None
                if key is None:
                    continue
                if bench_producer:
                    t.detail_keys.setdefault(key, []).append(
                        _site(path, k, key))
                if key == "kind":
                    kind = const_or_name(v)
                    if kind is not None:
                        t.kinds_produced.setdefault(kind, []).append(
                            _site(path, v, kind))
            continue
        # subscript stores: x["k"] = ... (bench keys + kind)
        if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                and isinstance(node.targets[0], ast.Subscript):
            key = _str_const(node.targets[0].slice)
            if key is not None:
                if bench_producer:
                    t.detail_keys.setdefault(key, []).append(
                        _site(path, node.targets[0], key))
                if key == "kind":
                    kind = const_or_name(node.value)
                    if kind is not None:
                        t.kinds_produced.setdefault(kind, []).append(
                            _site(path, node.value, kind))
            continue
        # comparisons: kind dispatch (==/!=/in/not in)
        if isinstance(node, ast.Compare) and len(node.comparators) == 1:
            left, op, right = (node.left, node.ops[0],
                               node.comparators[0])
            if isinstance(op, (ast.Eq, ast.NotEq)):
                for a, b in ((left, right), (right, left)):
                    if not _kind_expr(a, kind_vars):
                        continue
                    kind = const_or_name(b)
                    if kind is not None:
                        t.kinds_consumed.setdefault(kind, []).append(
                            _site(path, b, kind))
            elif isinstance(op, (ast.In, ast.NotIn)) \
                    and _kind_expr(left, kind_vars):
                for e in _str_tuple_elems(right) or ():
                    t.kinds_consumed.setdefault(e.value, []).append(
                        _site(path, e, e.value))
            continue
        if not isinstance(node, ast.Call):
            continue
        fn = node.func
        fname = fn.attr if isinstance(fn, ast.Attribute) else (
            fn.id if isinstance(fn, ast.Name) else "")

        # kind= producer keyword on any call (RunLog.emit, _emit, ...)
        for kw in node.keywords:
            if kw.arg == "kind":
                kind = const_or_name(kw.value)
                if kind is not None:
                    t.kinds_produced.setdefault(kind, []).append(
                        _site(path, kw.value, kind))
            # track=<int literal> call-site argument
            elif kw.arg == "track":
                track = _int_const(kw.value)
                if track is not None:
                    t.track_literals.append(
                        _site(path, kw.value, fname, str(track)))

        # metric producers: <registry>.gauge/counter/histogram("name")
        if isinstance(fn, ast.Attribute) and fn.attr in (
                "gauge", "counter", "histogram") and node.args:
            name = _str_const(node.args[0])
            if name is not None:
                t.gauges_produced.setdefault(name, []).append(
                    _site(path, node.args[0], name, fn.attr))
            elif isinstance(node.args[0], ast.JoinedStr):
                parts = node.args[0].values
                prefix = parts[0].value if parts and isinstance(
                    parts[0], ast.Constant) else ""
                if isinstance(prefix, str) and prefix:
                    t.gauge_prefixes.append(
                        _site(path, node.args[0], prefix, fn.attr))
        # metric consumers: gauges.get("mem.hbm_pages") — the base
        # name says which table is being read; dotted names only so
        # field lookups like g.get("n") never register
        elif isinstance(fn, ast.Attribute) and fn.attr == "get" \
                and node.args:
            base = _last_segment(mod, fn.value).lower()
            name = _str_const(node.args[0])
            if name is not None and "." in name and any(
                    b in base for b in ("gauge", "counter", "histogram",
                                        "hist")):
                t.gauges_consumed.append(
                    _site(path, node.args[0], name, base))
        # device-window producers: rec.mark_dispatch("serve.chunk",...)
        if fname in ("mark_dispatch", "mark_complete") and node.args:
            name = _str_const(node.args[0])
            if name is not None:
                t.spans_produced.setdefault(name, []).append(
                    _site(path, node.args[0], name, fname))
        # device-window consumers: _windows(records, "mem.prefetch")
        elif fname == "_windows" and len(node.args) >= 2:
            name = _str_const(node.args[1])
            if name is not None:
                t.spans_consumed.append(
                    _site(path, node.args[1], name))
        # gate-key consumers: MetricSpec("detail.x", ...)
        elif fname == "MetricSpec":
            spec_path = None
            if node.args:
                spec_path = _str_const(node.args[0])
            for kw in node.keywords:
                if kw.arg == "path":
                    spec_path = _str_const(kw.value)
            if spec_path is not None:
                anchor = node.args[0] if node.args else node
                gated = True
                for kw in node.keywords:
                    if kw.arg == "gated" and isinstance(
                            kw.value, ast.Constant):
                        gated = bool(kw.value.value)
                t.gate_specs.append(_site(
                    path, anchor, spec_path,
                    "gated" if gated else "informational"))
        # band references: track_band("migration")
        elif fname == "track_band" and node.args:
            name = _str_const(node.args[0])
            if name is not None:
                t.band_refs.append(_site(path, node.args[0], name))
        # chaos site claims: chaos.maybe_inject("collective", i), ...
        # recognized only when the call plausibly targets the chaos
        # module ("chaos" in the resolved name or the file name) —
        # `matching`/`suppress` are too generic to claim bare
        elif fname in _CHAOS_SITE_FUNCS and _is_chaos_call(mod, fn):
            if node.args and _str_const(node.args[0]) is not None:
                t.chaos_site_claims.append(
                    _site(path, node.args[0],
                          _str_const(node.args[0]), fname))
            if fname == "record_injection":
                kind = (_str_const(node.args[2])
                        if len(node.args) >= 3 else None)
                for kw in node.keywords:
                    if kw.arg == "kind":
                        kind = _str_const(kw.value)
                if kind is not None:
                    t.chaos_kind_claims.append(
                        _site(path, node, kind, fname))
            for kw in node.keywords:
                if kw.arg == "site" and _str_const(kw.value) is not None:
                    t.chaos_site_claims.append(
                        _site(path, kw.value, _str_const(kw.value),
                              fname))
        # chaos spec strings: configure("stall:at=3,...") and
        # chaos_spec="..." keywords anywhere
        if fname in _CHAOS_SPEC_FUNCS and node.args \
                and _is_chaos_call(mod, fn):
            _harvest_chaos_spec(t, path, node.args[0])
        for kw in node.keywords:
            if kw.arg in _CHAOS_SPEC_KWARGS:
                _harvest_chaos_spec(t, path, kw.value)
    return t


def _harvest_chaos_spec(t: ContractTables, path: str,
                        node: ast.AST) -> None:
    spec = _str_const(node)
    if spec is None:
        return
    for part in spec.split(";"):
        if _CHAOS_SPEC_RE.match(part.strip()):
            t.chaos_kind_claims.append(
                _site(path, node, part.strip().split(":", 1)[0],
                      "spec"))


# ---------------------------------------------------------------------------
# tree resolution + caching
# ---------------------------------------------------------------------------

_MODULE_CACHE: dict[tuple[str, int], ContractTables] = {}
_TREE_CACHE: dict[str, ContractTables] = {}


def _cached_extract(mod: ModuleInfo,
                    bench_producer: bool) -> ContractTables:
    key = (mod.path, hash((mod.source, bench_producer)))
    if key not in _MODULE_CACHE:
        _MODULE_CACHE[key] = extract_module(mod, bench_producer)
        if len(_MODULE_CACHE) > 512:
            _MODULE_CACHE.pop(next(iter(_MODULE_CACHE)))
    return _MODULE_CACHE[key]


def find_repo_root(path: str | Path) -> Path | None:
    """Nearest ancestor holding both ``bench.py`` and the
    ``hpc_patterns_tpu`` package — the live tree the tables merge
    over. None for a module outside any repo checkout."""
    p = Path(path).resolve()
    for parent in [p] + list(p.parents):
        if (parent / "bench.py").is_file() \
                and (parent / "hpc_patterns_tpu").is_dir():
            return parent
    return None


def _is_fixture(path: str | Path) -> bool:
    return "fixtures" in Path(path).parts


def tree_files(root: Path) -> list[tuple[Path, bool]]:
    """(file, is_bench_producer) for every harvested tree file:
    package + tests as producers/consumers of every contract EXCEPT
    gate keys, whose producer side is bench.py/benchmarks only."""
    out: list[tuple[Path, bool]] = []
    roots = [(root / "hpc_patterns_tpu", False),
             (root / "tests", False),
             (root / "bench.py", True),
             (root / "benchmarks", True)]
    for base, is_bench in roots:
        if not base.exists():
            continue
        for f in iter_python_files([base]):
            if _is_fixture(f):
                continue  # fixture corpora are their own trees
            out.append((f, is_bench))
    return out


def live_tables(root: Path) -> ContractTables:
    """The merged tables for one repo checkout, cached for the
    process lifetime (an analyzer run sees one immutable tree)."""
    key = str(root)
    if key in _TREE_CACHE:
        return _TREE_CACHE[key]
    tables = ContractTables(root=key)
    files: list[str] = []
    for f, is_bench in tree_files(root):
        try:
            mod = ModuleInfo.parse(f)
        except SyntaxError:
            continue  # parse-error is the engine's finding, not ours
        tables.merge(_cached_extract(mod, bench_producer=is_bench))
        files.append(str(f))
    tables.files = tuple(files)
    _TREE_CACHE[key] = tables
    return tables


def tables_for(mod: ModuleInfo) -> ContractTables:
    """The tables a rule should judge this module against: the live
    repo tree when the module belongs to one, the module alone when
    it is a fixture (or floats free of any checkout)."""
    if not _is_fixture(mod.path):
        root = find_repo_root(mod.path)
        if root is not None:
            return live_tables(root)
    tables = ContractTables()
    tables.merge(_cached_extract(mod, bench_producer=True))
    tables.files = (mod.path,)
    return tables


def tables_for_paths(paths) -> ContractTables:
    """The ``--contract-report`` entry point: the live tree's tables
    when the first path sits inside a repo checkout, else the merged
    tables of exactly the files given (every file a bench producer —
    the fixture/self-contained convention)."""
    paths = list(paths)
    root = find_repo_root(paths[0]) if paths else None
    if root is not None:
        return live_tables(root)
    tables = ContractTables()
    files: list[str] = []
    for f in iter_python_files(paths):
        try:
            mod = ModuleInfo.parse(f)
        except SyntaxError:
            continue
        tables.merge(_cached_extract(mod, bench_producer=True))
        files.append(str(f))
    tables.files = tuple(files)
    return tables


# ---------------------------------------------------------------------------
# --contract-report rendering
# ---------------------------------------------------------------------------


def _rel(path: str, root: str) -> str:
    try:
        return str(Path(path).relative_to(root)) if root else path
    except ValueError:
        return path


def _fmt_sites(sites: list[Site], root: str, limit: int = 2) -> str:
    locs = [f"{_rel(s.path, root)}:{s.line}" for s in sites[:limit]]
    extra = len(sites) - limit
    return ", ".join(locs) + (f" (+{extra})" if extra > 0 else "")


def format_contract_report(tables: ContractTables) -> str:
    """The informational twin of ``--vmem-report``: the full
    producer/consumer tables, one section per contract."""
    root = tables.root
    lines: list[str] = []
    lines.append(f"contractlint report over "
                 f"{len(tables.files)} file(s)"
                 + (f" [{root}]" if root else " [self-contained]"))

    lines.append("\ngate keys (harness/regress.py SPECS -> bench "
                 "detail emitters):")
    for s in tables.gate_specs:
        key = s.name.split(".", 1)[1] if s.name.startswith(
            "detail.") else s.name
        producers = tables.detail_keys.get(key, [])
        status = (_fmt_sites(producers, root) if producers
                  else "MISSING EMITTER")
        lines.append(f"  {s.name:<40} [{s.detail:<13}] <- {status}")

    lines.append("\nmetric names consumed by string "
                 "(report/explain/autofit) -> producers:")
    for s in sorted(tables.gauges_consumed,
                    key=lambda s: (s.name, s.path, s.line)):
        producers = tables.gauges_produced.get(s.name, [])
        status = (_fmt_sites(producers, root) if producers else
                  ("prefix match" if tables.gauge_has_producer(s.name)
                   else "MISSING PRODUCER"))
        lines.append(f"  {s.name:<40} @ "
                     f"{_rel(s.path, root)}:{s.line} <- {status}")
    for s in sorted(tables.spans_consumed,
                    key=lambda s: (s.name, s.path, s.line)):
        producers = tables.spans_produced.get(s.name, [])
        status = (_fmt_sites(producers, root) if producers
                  else "MISSING PRODUCER")
        lines.append(f"  {s.name:<40} @ "
                     f"{_rel(s.path, root)}:{s.line} <- {status} "
                     f"(device window)")

    lines.append("\nmetric names produced "
                 f"({len(tables.gauges_produced)} exact, "
                 f"{len(tables.gauge_prefixes)} f-string prefixes):")
    for name in sorted(tables.gauges_produced):
        lines.append(f"  {name:<40} "
                     f"{_fmt_sites(tables.gauges_produced[name], root)}")
    for s in sorted(tables.gauge_prefixes, key=lambda s: s.name):
        lines.append(f"  {s.name + '{...}':<40} "
                     f"{_rel(s.path, root)}:{s.line}")

    lines.append("\nRunLog record kinds (written vs dispatched):")
    all_kinds = sorted(set(tables.kinds_produced)
                       | set(tables.kinds_consumed)
                       | set(tables.forensic_kinds))
    for kind in all_kinds:
        p = tables.kinds_produced.get(kind, [])
        c = tables.kinds_consumed.get(kind, [])
        flags = []
        if not p:
            flags.append("NEVER WRITTEN")
        if not c:
            flags.append("forensic" if kind in tables.forensic_kinds
                         else "NEVER DISPATCHED")
        lines.append(
            f"  {kind:<28} written x{len(p):<3} dispatched "
            f"x{len(c):<3}" + (f"  [{', '.join(flags)}]" if flags
                               else ""))

    lines.append("\ndevice-subtrack bands (harness/trace.py "
                 "TRACK_BANDS):")
    for band in sorted(tables.declared_bands.values(),
                       key=lambda b: b.base):
        lines.append(f"  {band.name:<14} {band.base:>3}..{band.hi:<3} "
                     f"@ {_rel(band.site.path, root)}:{band.site.line}")
    if tables.band_literals:
        lines.append("  hand-written band bases (should come from "
                     "track_band):")
        for s in tables.band_literals:
            lines.append(f"    {s.name} = {s.detail} @ "
                         f"{_rel(s.path, root)}:{s.line}")

    lines.append("\nchaos contract (harness/chaos.py):")
    lines.append(f"  kinds: {', '.join(sorted(tables.chaos_kinds))}")
    lines.append(f"  sites: {', '.join(sorted(tables.chaos_sites))}")
    bad_sites = [s for s in tables.chaos_site_claims
                 if s.name not in tables.chaos_sites]
    bad_kinds = [s for s in tables.chaos_kind_claims
                 if s.name not in tables.chaos_kinds]
    lines.append(f"  site claims: {len(tables.chaos_site_claims)} "
                 f"({len(bad_sites)} unknown), kind claims: "
                 f"{len(tables.chaos_kind_claims)} "
                 f"({len(bad_kinds)} unknown)")
    return "\n".join(lines)
