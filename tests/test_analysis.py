"""jaxlint (hpc_patterns_tpu.analysis): golden fixture findings,
suppression semantics, the CI gate over the live package, and the
runtime donation-poison helper.

The fixture corpus under ``tests/fixtures/analysis/`` is the rule
catalog's executable form: one known-bad and one known-clean file per
rule, with expected findings marked line-exact by ``EXPECT: <rule>``
trailing comments — the golden comparison reads the markers, so a
fixture edit can't silently desynchronize from its expectations.
"""

from __future__ import annotations

import json
import re
from pathlib import Path

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from hpc_patterns_tpu.analysis import __main__ as cli
from hpc_patterns_tpu.analysis import core, runtime
from hpc_patterns_tpu.analysis.core import AnalysisConfig, ModuleInfo
from hpc_patterns_tpu.analysis.rules import _donor_table

FIXTURES = Path(__file__).parent / "fixtures" / "analysis"
PACKAGE = Path(__file__).resolve().parent.parent / "hpc_patterns_tpu"

_EXPECT_RE = re.compile(r"EXPECT:\s*([a-z\-]+(?:\s*,\s*[a-z\-]+)*)")


def _expected_findings() -> dict[tuple[str, int], set[str]]:
    """{(fixture name, line): {rules}} parsed from EXPECT markers."""
    expected: dict[tuple[str, int], set[str]] = {}
    for f in sorted(FIXTURES.glob("*.py")):
        for lineno, line in enumerate(f.read_text().splitlines(), 1):
            m = _EXPECT_RE.search(line)
            if m:
                expected[(f.name, lineno)] = {
                    r.strip() for r in m.group(1).split(",")}
    return expected


def _actual_findings() -> dict[tuple[str, int], set[str]]:
    report = core.run_paths([FIXTURES])
    actual: dict[tuple[str, int], set[str]] = {}
    for f in report.findings:
        actual.setdefault((Path(f.path).name, f.line), set()).add(f.rule)
    return actual


class TestGoldenFixtures:
    def test_findings_match_expect_markers_exactly(self):
        expected, actual = _expected_findings(), _actual_findings()
        assert expected, "fixture corpus lost its EXPECT markers"
        missing = {k: v for k, v in expected.items() if k not in actual}
        extra = {k: v for k, v in actual.items() if k not in expected}
        assert not missing and not extra, (
            f"missing={missing} extra={extra}")
        for key in expected:
            assert actual[key] == expected[key], (
                f"{key}: expected {expected[key]}, got {actual[key]}")

    def test_every_rule_demonstrated_by_a_caught_fixture(self):
        # the acceptance criterion: every hazard rule fires on the
        # corpus — the minimized PR 2 donation-alias replica AND the
        # minimized rank-branched-collective deadlock replica included
        caught = {r for rules in _actual_findings().values()
                  for r in rules}
        assert {"donation-alias", "host-sync-in-dispatch",
                "recompile-hazard", "prng-key-reuse",
                "tracer-leak", "collective-divergence",
                "collective-order", "unchecked-permutation",
                "spec-mismatch",
                # the pallaslint family (PR 13): every PR 8 chip-only
                # bug shape has a caught minimized replica
                "dma-sem-balance", "dma-slot-reuse",
                "collective-id-collision", "kernel-dtype-cast",
                "vmem-budget",
                # the contractlint family (PR 19): every stringly
                # producer/consumer seam has a caught drift replica
                "gate-key-orphan", "record-kind-drift",
                "wire-field-compat", "track-band-collision",
                "chaos-site-drift"} <= caught

    def test_rank_branched_deadlock_replica_is_caught_at_the_branch(self):
        live, _ = core.analyze_file(
            FIXTURES / "bad_collective_divergence.py")
        div = [f for f in live if f.rule == "collective-divergence"]
        assert len(div) == 3  # branch, early return, rank-sized loop
        src = (FIXTURES / "bad_collective_divergence.py").read_text()
        flagged = src.splitlines()[div[0].line - 1]
        assert "process_index" in flagged  # anchored at the branch

    def test_pr2_reproducer_is_caught_at_the_view_line(self):
        live, _ = core.analyze_file(
            FIXTURES / "bad_donation_alias.py")
        donation = [f for f in live if f.rule == "donation-alias"]
        assert donation, "the PR 2 reproducer must be flagged"
        src = (FIXTURES / "bad_donation_alias.py").read_text()
        flagged_line = src.splitlines()[donation[0].line - 1]
        assert "np.asarray(self.pos)" in flagged_line

    def test_clean_fixtures_stay_clean(self):
        for f in sorted(FIXTURES.glob("clean_*.py")):
            live, suppressed = core.analyze_file(f)
            assert not live, f"{f.name}: {[x.format() for x in live]}"
            assert not suppressed

    def test_findings_carry_location_and_hint(self):
        live, _ = core.analyze_file(FIXTURES / "bad_recompile.py")
        f = live[0]
        assert f.line > 0 and f.path.endswith("bad_recompile.py")
        assert f.hint  # every shipped rule must suggest the fix
        assert f"{f.path}:{f.line}" in f.format()


class TestSuppression:
    def test_named_suppressions_silence_and_are_counted(self):
        live, suppressed = core.analyze_file(FIXTURES / "suppressed.py")
        assert {f.rule for f in suppressed} == {
            "recompile-hazard", "host-sync-in-dispatch"}
        assert len(suppressed) == 2

    def test_bare_and_unknown_disable_are_findings(self):
        live, _ = core.analyze_file(FIXTURES / "suppressed.py")
        bad = [f for f in live if f.rule == "bad-suppression"]
        assert len(bad) == 2  # one bare, one unknown-rule
        # and the hazards under them stay LIVE
        assert sum(1 for f in live if f.rule == "recompile-hazard") == 2

    def test_standalone_suppression_skips_comment_lines(self):
        # the suppressed.py standalone form has a two-line
        # justification between the directive and the code
        _, suppressed = core.analyze_file(FIXTURES / "suppressed.py")
        assert any(f.rule == "host-sync-in-dispatch"
                   for f in suppressed)

    def test_bad_suppression_is_not_itself_suppressible(self, tmp_path):
        f = tmp_path / "m.py"
        f.write_text("x = 1  # jaxlint: disable  # jaxlint: disable\n")
        live, suppressed = core.analyze_file(f)
        assert any(x.rule == "bad-suppression" for x in live)


class TestEngine:
    def test_syntax_error_is_a_finding_not_a_crash(self, tmp_path):
        f = tmp_path / "broken.py"
        f.write_text("def f(:\n")
        live, _ = core.analyze_file(f)
        assert [x.rule for x in live] == ["parse-error"]

    def test_alias_resolution_sees_through_import_spellings(self):
        mod = ModuleInfo.parse(
            "m.py", "import numpy as xyz\nv = xyz.asarray(q)\n")
        call = mod.tree.body[1].value
        assert mod.resolve(call.func) == "numpy.asarray"

    def test_select_runs_only_named_rules(self):
        cfg = AnalysisConfig(select=frozenset({"prng-key-reuse"}))
        report = core.run_paths([FIXTURES], cfg)
        assert set(report.by_rule()) == {"prng-key-reuse"}

    def test_nested_function_hazard_reported_once(self, tmp_path):
        # rules walking nested defs see inner statements from both the
        # outer and inner function — the engine dedupes to one finding
        f = tmp_path / "nested.py"
        f.write_text(
            "from functools import partial\n"
            "import jax\n"
            "import numpy as np\n"
            "@partial(jax.jit, donate_argnums=(0,))\n"
            "def step(x):\n"
            "    return x\n"
            "def outer():\n"
            "    def inner(y):\n"
            "        v = np.asarray(y)\n"
            "        step(y)\n"
            "        return v.sum()\n"
            "    return inner\n")
        live, _ = core.analyze_file(f)
        assert [x.rule for x in live] == ["donation-alias"]

    def test_baseline_roundtrip_tolerates_known_findings(self, tmp_path):
        base = tmp_path / "baseline.json"
        report = core.run_paths([FIXTURES])
        core.write_baseline(base, report.findings)
        again = core.run_paths([FIXTURES],
                               baseline=core.load_baseline(base))
        assert not again.findings
        assert len(again.baselined) == len(report.findings)
        assert json.loads(base.read_text())["findings"]


class TestCLI:
    def test_ci_exits_nonzero_on_fixture_corpus(self, capsys):
        assert cli.main([str(FIXTURES), "--ci"]) == 1
        out = capsys.readouterr().out
        assert "donation-alias" in out and "jaxlint:" in out

    def test_ci_exits_zero_on_live_package(self, capsys):
        # THE tier-1 gate: the shipped tree is clean (fix-or-suppress
        # policy — no baseline file exists in the repo)
        assert cli.main([str(PACKAGE), "--ci"]) == 0
        out = capsys.readouterr().out
        assert "0 finding(s)" in out
        assert not (Path(__file__).resolve().parent.parent
                    / "jaxlint_baseline.json").exists()

    def test_default_paths_cover_the_package(self, capsys):
        assert cli.main(["--ci"]) == 0
        # the default target is the package dir: same file count as
        # pointing at it explicitly
        n = re.search(r"across (\d+) file",
                      capsys.readouterr().out).group(1)
        assert int(n) > 50

    def test_non_ci_mode_reports_but_exits_zero(self):
        assert cli.main([str(FIXTURES)]) == 0

    def test_select_rejects_unknown_rule_names(self, capsys):
        # a typo'd --select must not run zero rules and read clean
        assert cli.main([str(FIXTURES), "--ci",
                         "--select", "donation_alias"]) == 2
        assert "unknown rule(s)" in capsys.readouterr().err

    def test_log_appends_kind_analysis_record(self, tmp_path, capsys):
        log = tmp_path / "run.jsonl"
        log.write_text('{"kind": "result", "success": true}\n')
        cli.main([str(FIXTURES), "--log", str(log)])
        records = [json.loads(l) for l in
                   log.read_text().splitlines()]
        assert records[0]["kind"] == "result"  # appended, not truncated
        rec = records[-1]
        assert rec["kind"] == "analysis" and rec["ok"] is False
        assert rec["findings"] > 0 and rec["suppressed"] == 2
        assert rec["by_rule"]["donation-alias"] >= 1

    def test_list_rules_prints_catalog(self, capsys):
        assert cli.main(["--list-rules"]) == 0
        out = capsys.readouterr().out
        for rule in ("donation-alias", "host-sync-in-dispatch",
                     "recompile-hazard", "prng-key-reuse",
                     "tracer-leak", "collective-divergence",
                     "collective-order", "unchecked-permutation",
                     "spec-mismatch", "dma-sem-balance",
                     "dma-slot-reuse", "collective-id-collision",
                     "kernel-dtype-cast", "vmem-budget",
                     "gate-key-orphan", "record-kind-drift",
                     "wire-field-compat", "track-band-collision",
                     "chaos-site-drift"):
            assert rule in out

    def test_list_rules_groups_by_family(self, capsys):
        # the catalog is grouped: one header per rule family, every
        # family header before its first rule line
        assert cli.main(["--list-rules"]) == 0
        out = capsys.readouterr().out
        for family in ("jaxlint:", "shardlint:", "pallaslint:",
                       "contractlint:"):
            assert family in out
        lines = out.splitlines()
        contract = lines.index("contractlint:")
        section = {l.split()[0] for l in lines[contract + 1:]
                   if l.startswith("  ")}
        assert section == {"gate-key-orphan", "record-kind-drift",
                           "wire-field-compat",
                           "track-band-collision",
                           "chaos-site-drift"}


class TestBurnDownPins:
    """Regression pins for the analyzer's first full-package run: the
    true-positive fixes stay fixed."""

    def test_interop_app_jits_are_module_level(self):
        from hpc_patterns_tpu.apps import interop_app

        # hoisted wrappers: same object on every access = one trace
        # cache for the life of the process (the pre-fix form rebuilt
        # them inside run())
        assert interop_app._double is interop_app._double
        x = jnp.ones((8,), jnp.float32)
        np.testing.assert_allclose(np.asarray(interop_app._double(x)),
                                   2.0)
        np.testing.assert_allclose(np.asarray(interop_app._triple(x)),
                                   3.0)

    def test_rank_filled_reuses_its_jit(self, mesh8):
        from hpc_patterns_tpu.comm.communicator import Communicator
        from hpc_patterns_tpu.harness import trace as tracelib

        c = Communicator(mesh8, "x")
        a = c.rank_filled(16)
        b = c.rank_filled(16)
        assert len(c._rank_filled_cache) == 1
        fill = next(iter(c._rank_filled_cache.values()))
        # one compiled variant despite two calls
        assert tracelib.jit_cache_size(fill, strict=True) == 1
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
        c.rank_filled(32)
        assert len(c._rank_filled_cache) == 2

    def test_busy_wait_single_wrap_matches_oracle(self):
        from hpc_patterns_tpu.concurrency import kernels

        x = kernels.compute_buffer(8 * 128)
        got = kernels.busy_wait(x, 3)
        want = kernels.busy_wait_reference(x, 3)
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   rtol=1e-6)
        # tripcount is a runtime scalar: new values must NOT add
        # compiled variants (the autotuner contract)
        from hpc_patterns_tpu.harness import trace as tracelib

        n0 = tracelib.jit_cache_size(kernels._busy_wait_call,
                                     strict=True)
        kernels.busy_wait(x, 7)
        assert tracelib.jit_cache_size(kernels._busy_wait_call,
                                       strict=True) == n0


class TestPoisonDonated:
    def test_poison_breaks_stale_zero_copy_views(self):
        f = jax.jit(lambda v: v + 1, donate_argnums=(0,))
        x = jax.block_until_ready(jnp.arange(64, dtype=jnp.int32))
        view = np.asarray(x)  # zero-copy on CPU: the PR 2 shape
        orig = view.copy()
        pf = runtime.poison_donated(f, (0,))
        y = pf(x)
        # correctness preserved...
        np.testing.assert_array_equal(np.asarray(y), orig + 1)
        # ...and the stale view now reads EITHER the donated-in-place
        # output (donation honored) or the sentinel (poisoned): never
        # the comfortable pre-call values the bug class relies on
        assert not np.array_equal(view, orig)
        if pf.poison_count:
            assert view.view(np.uint32)[0] == 0xABABABAB

    def test_poison_skips_output_aliased_buffers(self):
        # identity-ish pytree: some leaves may alias outputs; the
        # helper must never corrupt what the caller receives
        f = jax.jit(lambda d: {"a": d["a"] * 2, "b": d["b"]},
                    donate_argnums=(0,))
        d = {"a": jnp.ones((16,)), "b": jnp.zeros((16,))}
        jax.block_until_ready(d)
        pf = runtime.poison_donated(f, (0,))
        out = pf(d)
        np.testing.assert_array_equal(np.asarray(out["a"]), 2.0)
        np.testing.assert_array_equal(np.asarray(out["b"]), 0.0)

    def test_wrapper_forwards_the_jit_cache_probe(self):
        from hpc_patterns_tpu.harness import trace as tracelib

        f = jax.jit(lambda v: v * 3, donate_argnums=(0,))
        pf = runtime.poison_donated(f, (0,))
        pf(jnp.ones((4,)))
        assert tracelib.jit_cache_size(pf, strict=True) == 1

    def test_targets_mirror_serving_donate_argnums(self):
        # SERVING_POISON_TARGETS must track models/serving.py — read
        # the donate_argnums straight out of the source with the
        # analyzer's own donor table (dogfood)
        serving_py = PACKAGE / "models" / "serving.py"
        donors = _donor_table(ModuleInfo.parse(serving_py))
        for name, argnums in runtime.SERVING_POISON_TARGETS.items():
            assert donors[name]["donate_argnums"] == argnums, name

    def test_install_serving_poison_roundtrip(self):
        from hpc_patterns_tpu.models import serving

        before = {n: getattr(serving, n)
                  for n in runtime.SERVING_POISON_TARGETS}
        uninstall = runtime.install_serving_poison()
        try:
            for n in runtime.SERVING_POISON_TARGETS:
                assert getattr(serving, n) is not before[n]
                assert getattr(serving, n).__wrapped__ is before[n]
        finally:
            uninstall()
        for n in runtime.SERVING_POISON_TARGETS:
            assert getattr(serving, n) is before[n]


class TestShardlintRules:
    """Engine-level behaviors of the collective-divergence rule family
    that the fixture corpus doesn't pin line-exact."""

    def _live(self, src, tmp_path):
        f = tmp_path / "m.py"
        f.write_text(src)
        live, _ = core.analyze_file(f)
        return live

    def test_taint_flows_through_assignment_chains(self, tmp_path):
        live = self._live(
            "from jax import lax\n"
            "def f(comm, x):\n"
            "    me = lax.axis_index('x')\n"
            "    is_root = me == 0\n"
            "    if is_root:\n"
            "        return comm.allreduce(x)\n"
            "    return comm.sendrecv_ring(x)\n",
            tmp_path)
        assert [x.rule for x in live] == ["collective-divergence"]

    def test_launcher_env_rank_read_is_a_rank_source(self, tmp_path):
        live = self._live(
            "import os\n"
            "def f(comm, x):\n"
            "    if int(os.environ['HPCPAT_PROCESS_ID']) == 0:\n"
            "        comm.allreduce(x)\n",
            tmp_path)
        assert [x.rule for x in live] == ["collective-divergence"]

    def test_rank_guarded_raise_is_exempt(self, tmp_path):
        # precondition checks kill the job loudly; they are not the
        # quiet-deadlock shape the rule hunts
        live = self._live(
            "import jax\n"
            "def f(comm, x, size):\n"
            "    if jax.process_index() >= size:\n"
            "        raise ValueError('rank out of range')\n"
            "    return comm.allreduce(x)\n",
            tmp_path)
        assert not live

    def test_nested_uniform_branch_counts_once_not_twice(self, tmp_path):
        # a data-dependent inner branch whose arms issue the SAME
        # collective must not flatten to [allreduce, allreduce] and
        # fake a divergence against the else-arm's single allreduce
        live = self._live(
            "import jax\n"
            "def f(comm, x, c):\n"
            "    if jax.process_index() == 0:\n"
            "        if c:\n"
            "            y = comm.allreduce(x)\n"
            "        else:\n"
            "            y = comm.allreduce(-x)\n"
            "    else:\n"
            "        y = comm.allreduce(x * 2)\n"
            "    return y\n",
            tmp_path)
        assert not live

    def test_unjudgeable_nested_branch_abstains(self, tmp_path):
        # an inner UNIFORM branch whose arms genuinely differ (an
        # algorithm switch) makes the outer comparison unjudgeable:
        # abstain rather than guess — and rather than false-positive
        live = self._live(
            "import jax\n"
            "def f(comm, x, use_ring):\n"
            "    if jax.process_index() == 0:\n"
            "        if use_ring:\n"
            "            y = comm.sendrecv_ring(x)\n"
            "        else:\n"
            "            y = comm.all_gather(x)\n"
            "    else:\n"
            "        y = comm.allreduce(x)\n"
            "    return y\n",
            tmp_path)
        assert not live

    def test_order_rule_needs_same_multiset(self, tmp_path):
        # different op SETS across arms is an algorithm switch, not a
        # reordering — neither order nor divergence (uniform predicate)
        live = self._live(
            "def f(comm, x, fast):\n"
            "    if fast:\n"
            "        return comm.allreduce(x)\n"
            "    return comm.reduce_scatter(x)\n",
            tmp_path)
        assert not live

    def test_spec_checks_skip_open_world_modules(self, tmp_path):
        # a module building meshes from caller-provided axis names can
        # never have its spec literals judged (topology.py's shape)
        live = self._live(
            "from jax.sharding import Mesh, PartitionSpec as P\n"
            "def f(devs, names):\n"
            "    mesh = Mesh(devs, names)\n"
            "    return P('anything', None)\n",
            tmp_path)
        assert not live

    def test_ppermute_check_in_another_scope_does_not_count(self, tmp_path):
        live = self._live(
            "from jax import lax\n"
            "from hpc_patterns_tpu.comm.ring import check_permutation\n"
            "def checker(pairs, size):\n"
            "    check_permutation(pairs, size)\n"
            "def f(x, pairs):\n"
            "    return lax.ppermute(x, 'x', pairs)\n",
            tmp_path)
        assert [x.rule for x in live] == ["unchecked-permutation"]


class TestCollectiveSchedule:
    """The runtime verifier's hash chain: equality means equal
    schedules, any fingerprint field divergence changes the digest,
    and the launcher progress-file protocol works without jax."""

    def test_identical_records_identical_digests(self):
        a, b = runtime.CollectiveSchedule(), runtime.CollectiveSchedule()
        for s in (a, b):
            s.record("allreduce.ring", 0, shape=(2, 8),
                     dtype="float32", axis="x")
            s.record("sendrecv_ring", 1, shape=(2, 8),
                     dtype="float32", axis="x")
        assert a.digest == b.digest
        assert a.n == b.n == 2
        assert a.last["op"] == "sendrecv_ring"

    def test_every_fingerprint_field_feeds_the_digest(self):
        base = dict(shape=(2, 8), dtype="float32", axis="x")
        digests = set()
        for op, seq, kw in [
            ("allreduce.ring", 0, base),
            ("sendrecv_ring", 0, base),                  # op differs
            ("allreduce.ring", 1, base),                 # seq differs
            ("allreduce.ring", 0, {**base, "shape": (2, 16)}),
            ("allreduce.ring", 0, {**base, "dtype": "int32"}),
            ("allreduce.ring", 0, {**base, "axis": "y"}),
        ]:
            s = runtime.CollectiveSchedule()
            s.record(op, seq, **kw)
            digests.add(s.digest)
        assert len(digests) == 6

    def test_window_bounds_entries_not_the_digest(self):
        s = runtime.CollectiveSchedule(window=4)
        for i in range(10):
            s.record("op", i)
        assert s.n == 10
        assert len(s.entries) == 4
        assert s.entries[0]["i"] == 6  # absolute indices survive
        full = runtime.CollectiveSchedule()
        for i in range(10):
            full.record("op", i)
        assert s.digest == full.digest  # digest covers full history

    def test_progress_file_written_under_launcher_env(
            self, tmp_path, monkeypatch):
        monkeypatch.setenv(runtime.ENV_TRACE_DIR, str(tmp_path))
        monkeypatch.setenv(runtime.ENV_PROCESS_ID, "3")
        runtime.reset_collective_schedule()
        try:
            runtime.record_collective("allreduce.ring", 7,
                                      shape=(2, 8), dtype="float32",
                                      axis="x")
            rec = json.loads(
                (tmp_path / "rank00003.sched.json").read_text())
            assert rec["process_id"] == 3 and rec["n"] == 1
            assert rec["last"] == {"i": 0, "op": "allreduce.ring",
                                   "seq": 7}
            assert rec["digest"]
        finally:
            runtime.reset_collective_schedule()

    def test_env_names_mirror_topology_constants(self):
        # runtime duplicates the literals to stay importable without
        # jax; the pair must never drift from the launcher protocol
        from hpc_patterns_tpu import topology

        assert runtime.ENV_TRACE_DIR == topology.ENV_TRACE_DIR
        assert runtime.ENV_PROCESS_ID == topology.ENV_PROCESS_ID

    def test_eager_communicator_collectives_are_fingerprinted(
            self, mesh8):
        from hpc_patterns_tpu.comm.communicator import Communicator
        from hpc_patterns_tpu.harness import trace as tracelib

        # recording engages only when something can consume the chain
        # (a live recorder, or a launcher trace dir) — configure()
        # also resets the chain to genesis
        tracelib.configure(enabled=True)
        try:
            comm = Communicator(mesh8, "x")
            x = comm.rank_filled(8)
            comm.allreduce(x)
            comm.sendrecv_ring(x)
            sched = runtime.collective_schedule()
            assert [e["op"] for e in sched.entries] == [
                "allreduce.collective", "sendrecv_ring"]
            e = sched.entries[0]
            assert e["seq"] == 0 and e["axis"] == "x"
            assert e["shape"] == [8, 8]
            assert e["dtype"] == "float32"
        finally:
            tracelib.configure(enabled=False)

    def test_untraced_eager_collectives_stay_unrecorded(self, mesh8,
                                                        monkeypatch):
        # the disabled-path contract: no recorder, no launcher trace
        # dir -> no lock, no hash, no entry (byte-identical hot path)
        from hpc_patterns_tpu.comm.communicator import Communicator
        from hpc_patterns_tpu.harness import trace as tracelib

        monkeypatch.delenv(runtime.ENV_TRACE_DIR, raising=False)
        tracelib.configure(enabled=False)
        comm = Communicator(mesh8, "x")
        comm.allreduce(comm.rank_filled(4))
        assert runtime.collective_schedule().n == 0

    def test_trace_snapshot_stamps_the_chain(self):
        from hpc_patterns_tpu.harness import trace as tracelib

        runtime.reset_collective_schedule()
        try:
            runtime.record_collective("allreduce.ring", 0)
            snap = tracelib.TraceRecorder(enabled=True).snapshot()
            assert snap["collectives"]["n"] == 1
            assert snap["collectives"]["digest"]
            assert snap["collectives"]["entries"][0]["op"] == \
                "allreduce.ring"
        finally:
            runtime.reset_collective_schedule()

    def test_trace_configure_resets_the_chain(self):
        from hpc_patterns_tpu.harness import trace as tracelib

        runtime.record_collective("anything", 0)
        tracelib.configure(enabled=False)
        assert runtime.collective_schedule().n == 0


class TestMarker:
    def test_dispatch_critical_is_a_noop_marker(self):
        from hpc_patterns_tpu.analysis import dispatch_critical

        def g(x):
            return x + 1

        assert dispatch_critical(g) is g


class TestPallasLedger:
    """Engine-level behaviors of the semaphore-ledger abstract
    interpreter (analysis/pallas_rules.py) beyond the line-exact
    fixture corpus."""

    def _ledger(self, path):
        from hpc_patterns_tpu.analysis import pallas_rules as pr

        return pr.ledger_findings(ModuleInfo.parse(path))

    def test_live_kernel_tier_is_clean(self):
        # the burn-down target: the fused rings, the flash/paged/MLP
        # kernels, and the on-chip pipeline all balance
        for rel in ("comm/fused.py", "concurrency/pipeline.py",
                    "concurrency/kernels.py", "ops/flash_attention.py",
                    "ops/flash_decode.py", "ops/fused_mlp.py",
                    "ops/paged_attention.py"):
            findings = self._ledger(PACKAGE / rel)
            assert not findings, (rel, [(k, n.lineno, m)
                                        for k, n, m in findings])

    def test_fused_kernels_are_analyzed_not_abstained(self):
        # 0 findings must mean "proved balanced", not "gave up": the
        # interpreter must actually record DMA signals for every
        # fused kernel root
        from hpc_patterns_tpu.analysis import pallas_rules as pr

        mod = ModuleInfo.parse(PACKAGE / "comm" / "fused.py")
        roots = pr._kernel_roots(mod)
        assert len(roots) == 3  # permute, allreduce, allgather_matmul
        signals = {"n": 0}
        orig = pr._KernelRun._signal

        def counting(self, key, node, _orig=orig):
            signals["n"] += 1
            return _orig(self, key, node)

        pr._KernelRun._signal = counting
        try:
            for fn in roots:
                before = signals["n"]
                assert pr._analyze_kernel(mod, fn) == []
                assert signals["n"] > before, (
                    f"kernel at line {fn.lineno} abstained")
        finally:
            pr._KernelRun._signal = orig

    def test_model_ring_covers_the_drain_bug_threshold(self):
        # the PR 8 drain double-wait manifests at size >= 3; the
        # modeled ring must be past it or the fixture could pass
        from hpc_patterns_tpu.analysis import pallas_rules as pr

        assert pr.MODEL_RING >= 3

    def test_drain_double_wait_anchored_at_the_drain(self):
        live, _ = core.analyze_file(FIXTURES / "bad_pallas_dma.py")
        balance = [f for f in live if f.rule == "dma-sem-balance"]
        assert balance, "the PR 8 drain replica must be flagged"
        src = (FIXTURES / "bad_pallas_dma.py").read_text()
        flagged = src.splitlines()[balance[0].line - 1]
        assert "wait_send" in flagged  # the re-wait, not the loop head

    def test_phase_crossed_recv_names_both_sem_families(self):
        live, _ = core.analyze_file(FIXTURES / "bad_pallas_dma.py")
        reuse = [f for f in live if f.rule == "dma-slot-reuse"
                 and "semaphore families" in f.message]
        assert len(reuse) == 1
        assert "rs_sem" in reuse[0].message
        assert "ag_sem" in reuse[0].message

    def test_opaque_loop_with_dma_abstains_not_guesses(self, tmp_path):
        # a DMA under a loop the interpreter cannot unroll (opaque
        # iterable, not a range) must produce silence, not findings
        f = tmp_path / "m.py"
        f.write_text(
            "from jax.experimental import pallas as pl\n"
            "from jax.experimental.pallas import tpu as pltpu\n"
            "def run(x, schedule):\n"
            "    def kernel(x_ref, o_ref, buf, sem):\n"
            "        for hop in schedule:\n"
            "            d = pltpu.make_async_copy(\n"
            "                x_ref, buf.at[0], sem.at[0])\n"
            "            d.start()\n"
            "    return pl.pallas_call(kernel, out_shape=x)(x)\n")
        live, _ = core.analyze_file(f)
        assert not live

    def test_mode_switch_predicates_stay_consistent(self, tmp_path):
        # a factory kernel branching on one opaque subject must not
        # fork into impossible combinations (mode == 'a' AND
        # mode == 'b') and fake an imbalance — the pipeline.py shape
        f = tmp_path / "m.py"
        f.write_text(
            "from jax.experimental import pallas as pl\n"
            "from jax.experimental.pallas import tpu as pltpu\n"
            "def make(mode):\n"
            "    def kernel(x_ref, o_ref, buf, sem):\n"
            "        d = pltpu.make_async_copy(x_ref, buf.at[0],\n"
            "                                  sem.at[0])\n"
            "        if mode == 'eager':\n"
            "            d.start()\n"
            "            d.wait()\n"
            "        if mode != 'eager':\n"
            "            pass\n"
            "    return kernel\n"
            "def run(x, mode):\n"
            "    return pl.pallas_call(make(mode), out_shape=x)(x)\n")
        live, _ = core.analyze_file(f)
        assert not live

    def test_magic_collective_id_flagged_registry_call_not(self,
                                                           tmp_path):
        f = tmp_path / "m.py"
        f.write_text(
            "from hpc_patterns_tpu.ops.tiling import collective_id\n"
            "def a(params):\n"
            "    return params(collective_id=7)\n"
            "def b(params):\n"
            "    return params(\n"
            "        collective_id=collective_id('x.y'))\n")
        live, _ = core.analyze_file(f)
        assert [x.rule for x in live] == ["collective-id-collision"]
        assert "7" in live[0].message

    def test_duplicate_registry_names_collide(self, tmp_path):
        # two call sites registering the SAME name is the shared-id
        # bug wearing the registry's clothes — still flagged
        f = tmp_path / "m.py"
        f.write_text(
            "from hpc_patterns_tpu.ops.tiling import collective_id\n"
            "def a(params):\n"
            "    return params(collective_id=collective_id('k'))\n"
            "def b(params):\n"
            "    return params(collective_id=collective_id('k'))\n")
        live, _ = core.analyze_file(f)
        assert [x.rule for x in live] == ["collective-id-collision"]
        assert "'k'" in live[0].message


class TestCollectiveIdRegistry:
    def test_historical_ids_are_pinned(self):
        # the shipped kernels' wire ids must never move: 0-4 as
        # hand-numbered before the registry existed
        from hpc_patterns_tpu.ops import tiling

        ids = tiling.registered_collective_ids()
        assert ids["comm.fused.permute"] == 0
        assert ids["comm.fused.allreduce"] == 1
        assert ids["comm.fused.allgather_matmul"] == 2
        assert ids["parallel.ring_attention.kshift"] == 3
        assert ids["parallel.ring_attention.vshift"] == 4

    def test_new_names_get_distinct_ids_idempotently(self):
        from hpc_patterns_tpu.ops import tiling

        a = tiling.collective_id("test.registry.alpha")
        b = tiling.collective_id("test.registry.beta")
        assert a != b
        assert tiling.collective_id("test.registry.alpha") == a
        ids = tiling.registered_collective_ids()
        assert len(set(ids.values())) == len(ids)  # never a collision

    def test_new_ids_are_name_derived_not_order_derived(self):
        # every host of an SPMD job must compute the same id for a
        # name regardless of which kernel warms up first — the id is
        # a pure function of the string, above the seeded block
        from hpc_patterns_tpu.ops import tiling

        a = tiling._derived_id("test.order.a")
        b = tiling._derived_id("test.order.b")
        assert a != b
        assert min(a, b) >= tiling._ID_FLOOR
        assert tiling.collective_id("test.order.b") == b  # b first
        assert tiling.collective_id("test.order.a") == a
        assert tiling._derived_id("test.order.a") == a  # deterministic

    def test_registry_names_globally_unique_across_package(self):
        # the cross-module half of collective-id-collision: the lint
        # rule is per-module by engine design, so the whole-package
        # invariant — no two call sites registering one name — is
        # pinned here instead
        import ast as astmod

        registry_fns = ("collective_id", "_registered_collective_id")
        sites: dict[str, list[str]] = {}
        for path in sorted(PACKAGE.rglob("*.py")):
            tree = astmod.parse(path.read_text())
            for node in astmod.walk(tree):
                if not (isinstance(node, astmod.Call) and node.args
                        and isinstance(node.args[0], astmod.Constant)):
                    continue
                # both spellings count: bare collective_id(...) and
                # tiling.collective_id(...) (the attribute form
                # parallel/ring_attention.py uses)
                func = node.func
                name = (func.id if isinstance(func, astmod.Name)
                        else func.attr
                        if isinstance(func, astmod.Attribute) else "")
                if name in registry_fns:
                    sites.setdefault(str(node.args[0].value), []).append(
                        f"{path.name}:{node.lineno}")
        assert sites, "the registry call sites vanished"
        dupes = {k: v for k, v in sites.items() if len(v) > 1}
        assert not dupes, dupes


class TestVmemEstimator:
    """The budget estimator (analysis/vmem.py): the paged_flash golden
    bound, full-package coverage, and the literal lower-bound rule."""

    def test_paged_flash_row_reproduces_the_docs_bound(self):
        # docs/quantization.md: the gather scratch holds the whole
        # allocated span — pages·P·D of pool dtype for K and V each.
        # At S_alloc = pages·P = 16384, D = 128 that is 4 MiB for int8
        # pools (plus the two (1, pages·P) f32 scale rows)
        from hpc_patterns_tpu.analysis import vmem

        mod = ModuleInfo.parse(PACKAGE / "ops" / "paged_attention.py")
        (est,) = vmem.estimate_module(mod)
        assert est.kernel == "_paged_attention_kernel"
        bindings = {"pages": 128, "P": 128, "D": 128}
        spans = [c for c in est.components
                 if c.label.startswith("scratch")]
        assert len(spans) == 4  # K span, V span, 2 scale rows
        kv_bytes = 0
        scale_bytes = 0
        for c in spans:
            n, assumed = vmem.q_value(c.quantity, bindings)
            assert not assumed, (c.label, assumed)
            if c.dtype_bytes == 4:       # the f32 scale rows
                scale_bytes += n * 4
            else:                        # pool-dtype spans at int8
                kv_bytes += n * 1
        assert kv_bytes == 2 * 16384 * 128          # 4 MiB exactly
        assert scale_bytes == 2 * 16384 * 4
        # and at the f32 default the same spans blow the 16 MB scoped
        # limit — the documented "f32 pools belong on the streaming
        # route", now a number instead of a sentence
        total, _ = est.model_bytes(bindings)
        assert total > est.limit_bytes

    def test_every_package_pallas_call_gets_a_numeric_row(self):
        # the acceptance criterion: per-kernel byte totals for EVERY
        # pallas_call under model bindings — no silent gaps
        from hpc_patterns_tpu.analysis import vmem

        ests = vmem.estimate_paths([PACKAGE])
        by_file = {Path(e.path).name for e in ests}
        assert {"fused.py", "pipeline.py", "kernels.py", "device.py",
                "flash_attention.py", "flash_decode.py",
                "fused_mlp.py", "paged_attention.py"} <= by_file
        assert len(ests) >= 12
        for est in ests:
            total, _ = est.model_bytes()
            assert total > 0, (est.kernel, est.path)

    def test_explicit_vmem_limit_is_read(self):
        from hpc_patterns_tpu.analysis import vmem

        mod = ModuleInfo.parse(PACKAGE / "comm" / "fused.py")
        ests = {e.line: e for e in vmem.estimate_module(mod)}
        limits = {e.limit_bytes for e in ests.values()
                  if not e.limit_default}
        assert 100 * 1024 * 1024 in limits  # fused.py's _VMEM_LIMIT

    def test_lower_bound_rule_needs_literals(self, tmp_path):
        # symbolic shapes never fire the rule (the report's job), and
        # a literal overflow always does
        f = tmp_path / "m.py"
        f.write_text(
            "import jax, jax.numpy as jnp\n"
            "from jax.experimental import pallas as pl\n"
            "from jax.experimental.pallas import tpu as pltpu\n"
            "def k(x_ref, o_ref, acc):\n"
            "    o_ref[...] = x_ref[...]\n"
            "def sym(x, n):\n"
            "    return pl.pallas_call(k, out_shape=x,\n"
            "        scratch_shapes=[pltpu.VMEM((n, n), jnp.float32)],\n"
            "    )(x)\n"
            "def lit(x):\n"
            "    return pl.pallas_call(k, out_shape=x,\n"
            "        scratch_shapes=[\n"
            "            pltpu.VMEM((8192, 8192), jnp.float32)],\n"
            "    )(x)\n")
        live, _ = core.analyze_file(f)
        assert [x.rule for x in live] == ["vmem-budget"]
        assert "268,435,456" in live[0].message

    def test_unrelated_scope_never_resolves_runtime_dims(self,
                                                         tmp_path):
        # scope correctness: another function's local ``n = 8192``
        # (or a module constant shadowed by a parameter) must not
        # resolve this kernel's RUNTIME ``n`` into a literal — that
        # would fire the CI-gating rule on correct code
        f = tmp_path / "m.py"
        f.write_text(
            "import jax, jax.numpy as jnp\n"
            "from jax.experimental import pallas as pl\n"
            "from jax.experimental.pallas import tpu as pltpu\n"
            "n = 8192\n"
            "def unrelated():\n"
            "    m = 8192\n"
            "    return m\n"
            "def k(x_ref, o_ref, acc):\n"
            "    o_ref[...] = x_ref[...]\n"
            "def run_param(x, n):\n"
            "    return pl.pallas_call(k, out_shape=x,\n"
            "        scratch_shapes=[pltpu.VMEM((n, n), jnp.float32)],\n"
            "    )(x)\n"
            "def run_other(x, m):\n"
            "    return pl.pallas_call(k, out_shape=x,\n"
            "        scratch_shapes=[pltpu.VMEM((m, m), jnp.float32)],\n"
            "    )(x)\n")
        live, _ = core.analyze_file(f)
        assert not live

    def test_format_table_names_assumed_symbols(self):
        from hpc_patterns_tpu.analysis import vmem

        ests = vmem.estimate_paths([PACKAGE / "ops"])
        table = vmem.format_vmem_table(ests, root=PACKAGE.parent)
        assert "_paged_attention_kernel" in table
        assert "ASSUMED" in table  # runtime dtypes are never silent
        assert "vmem bytes" in table

    def test_vmem_summary_is_json_able(self):
        from hpc_patterns_tpu.analysis import vmem

        ests = vmem.estimate_paths([PACKAGE / "comm"])
        summary = vmem.vmem_summary(ests)
        json.dumps(summary)
        assert summary["kernels"] == len(ests) >= 3
        assert all(r["bytes"] > 0 for r in summary["rows"])


class TestStrictSemaphores:
    """The strict-semaphore interpret shim (analysis/runtime.py): the
    PR 8 balance bug class fails at TRACE time under the shim. The
    fused parity battery runs under it module-wide
    (tests/test_fused_comm.py); these pin the shim's own semantics."""

    def _run_kernel(self, kernel, mesh8, extra_scratch=2):
        from jax.experimental import pallas as pl
        from jax.experimental.pallas import tpu as pltpu
        from jax.sharding import PartitionSpec as P
        from hpc_patterns_tpu.topology import shard_map

        x = jnp.arange(8 * 2 * 8, dtype=jnp.float32).reshape(16, 8)

        def run(v):
            return pl.pallas_call(
                kernel,
                out_shape=jax.ShapeDtypeStruct(v.shape, v.dtype),
                in_specs=[pl.BlockSpec(memory_space=pltpu.VMEM)],
                out_specs=pl.BlockSpec(memory_space=pltpu.VMEM),
                scratch_shapes=[pltpu.VMEM(v.shape, v.dtype)]
                + [pltpu.SemaphoreType.DMA] * extra_scratch,
                interpret=True,
            )(v)

        f = jax.jit(shard_map(run, mesh=mesh8, in_specs=P("x"),
                              out_specs=P("x")))
        return jax.block_until_ready(f(x))

    def test_balanced_kernel_passes_and_is_counted(self, mesh8):
        from jax import lax
        from jax.experimental.pallas import tpu as pltpu

        def kernel(x_ref, o_ref, buf, send_sem, recv_sem):
            me = lax.axis_index("x")
            d = pltpu.make_async_remote_copy(
                src_ref=x_ref, dst_ref=o_ref, send_sem=send_sem,
                recv_sem=recv_sem, device_id=lax.rem(me + 1, 8),
                device_id_type=pltpu.DeviceIdType.LOGICAL)
            d.start()
            d.wait()

        with runtime.strict_semaphores() as ledger:
            self._run_kernel(kernel, mesh8)
        assert ledger.kernels_checked == 1

    def test_undrained_send_fails_at_trace_time(self, mesh8):
        from jax import lax
        from jax.experimental.pallas import tpu as pltpu

        def kernel(x_ref, o_ref, buf, send_sem, recv_sem):
            me = lax.axis_index("x")
            d = pltpu.make_async_remote_copy(
                src_ref=x_ref, dst_ref=buf, send_sem=send_sem,
                recv_sem=recv_sem, device_id=lax.rem(me + 1, 8),
                device_id_type=pltpu.DeviceIdType.LOGICAL)
            d.start()
            d.wait_recv()          # BUG: the send is never waited
            o_ref[...] = buf[...]

        with runtime.strict_semaphores():
            with pytest.raises(runtime.SemaphoreBalanceError,
                               match="send wait"):
                self._run_kernel(kernel, mesh8)

    def test_drain_double_wait_fails_at_trace_time(self, mesh8):
        # the PR 8 drain bug's exact shape: one descriptor's send
        # semaphore waited twice
        from jax import lax
        from jax.experimental.pallas import tpu as pltpu

        def kernel(x_ref, o_ref, buf, send_sem, recv_sem):
            me = lax.axis_index("x")
            d = pltpu.make_async_remote_copy(
                src_ref=x_ref, dst_ref=o_ref, send_sem=send_sem,
                recv_sem=recv_sem, device_id=lax.rem(me + 1, 8),
                device_id_type=pltpu.DeviceIdType.LOGICAL)
            d.start()
            d.wait()
            d.wait_send()          # BUG: one signal per DMA

        with runtime.strict_semaphores():
            with pytest.raises(runtime.SemaphoreBalanceError,
                               match="waited 2 times"):
                self._run_kernel(kernel, mesh8)

    def test_local_copy_balance_is_checked_too(self, mesh8):
        from jax.experimental.pallas import tpu as pltpu

        def kernel(x_ref, o_ref, buf, sem, _unused):
            d = pltpu.make_async_copy(x_ref, buf, sem)
            d.start()              # BUG: never waited
            o_ref[...] = x_ref[...]

        with runtime.strict_semaphores():
            with pytest.raises(runtime.SemaphoreBalanceError,
                               match="local start"):
                self._run_kernel(kernel, mesh8)

    def test_shim_uninstalls_cleanly(self):
        from jax.experimental import pallas as pl
        from jax.experimental.pallas import tpu as pltpu

        before = (pltpu.make_async_copy, pltpu.make_async_remote_copy,
                  pl.pallas_call)
        with runtime.strict_semaphores():
            assert pl.pallas_call is not before[2]
        assert (pltpu.make_async_copy, pltpu.make_async_remote_copy,
                pl.pallas_call) == before


class TestContractlint:
    """Whole-tree producer/consumer verification (contractlint): the
    static tables agree with the live tree, and the motivating
    deleted-emitter shape is caught at the surviving gate row."""

    def test_static_gate_key_table_covers_every_gate_spec(self):
        # the static twin of regress.py's runtime coverage-loss
        # warning: every detail.* key the gate table consumes must
        # have an emitter in bench.py/benchmarks/ BEFORE any bench
        # run happens — a deleted emitter fails here, not one silent
        # bench run later
        from hpc_patterns_tpu.analysis import contracts
        from hpc_patterns_tpu.harness import regress

        root = contracts.find_repo_root(Path(__file__).resolve())
        assert root is not None
        tables = contracts.live_tables(root)
        for spec in regress.SPECS:
            if not spec.path.startswith("detail."):
                continue
            key = spec.path.split(".", 1)[1]
            assert key in tables.detail_keys, (
                f"gate key {spec.path} has no static emitter in "
                f"bench.py/benchmarks/")

    def test_deleted_emitter_replica_flagged_at_the_gate_row(self):
        # the minimized "gated key whose emitter was deleted" replica:
        # the finding anchors at the surviving MetricSpec row, exactly
        # where its EXPECT marker sits
        path = FIXTURES / "bad_gate_key_orphan.py"
        live, _ = core.analyze_file(path)
        orphans = [f for f in live if f.rule == "gate-key-orphan"]
        assert orphans, "the deleted-emitter replica must be flagged"
        lines = path.read_text().splitlines()
        gate_rows = [f for f in orphans
                     if "detail.engine_bubble_frac" in lines[f.line - 1]]
        assert gate_rows, "finding must anchor at the gate-table row"
        assert "EXPECT: gate-key-orphan" in lines[gate_rows[0].line - 1]

    def test_fixture_worlds_are_self_contained(self):
        # a fixture under tests/fixtures/ is its own single-module
        # tree: its tables must not bleed into (or read from) the
        # live repo tables
        from hpc_patterns_tpu.analysis import contracts

        mod = core.ModuleInfo.parse(
            FIXTURES / "bad_record_kind_drift.py")
        t = contracts.tables_for(mod)
        assert set(t.kinds_produced) == {"engine_round", "engine_debug"}
        assert t.root == ""  # not resolved to the repo checkout

    def test_live_wire_codec_declares_required_fields(self):
        # REQUIRED_WIRE_FIELDS is the explicit absent-intolerance
        # contract: direct indexing in from_wire is legal only for
        # declared fields
        from hpc_patterns_tpu.serving_plane import migration

        assert "seq_id" in migration.REQUIRED_WIRE_FIELDS
        assert "payload" in migration.REQUIRED_WIRE_FIELDS

    def test_live_track_bands_registry_is_collision_free(self):
        from hpc_patterns_tpu.harness import trace as tracelib

        bands = sorted(tracelib.TRACK_BANDS.items(),
                       key=lambda kv: kv[1][0])
        for (_, (b0, n0)), (_, (b1, _)) in zip(bands, bands[1:]):
            assert b0 + n0 <= b1, f"bands overlap: {bands}"
        # the three migrated modules unpack from the registry
        from hpc_patterns_tpu.memory import residency
        from hpc_patterns_tpu.serving_plane import autoscaler, service

        assert (service.MIG_TRACK_BASE, service.MIG_TRACKS) \
            == tracelib.track_band("migration")
        assert (autoscaler.SPINUP_TRACK_BASE, autoscaler.SPINUP_TRACKS) \
            == tracelib.track_band("spinup")
        assert (residency.MEM_TRACK_BASE, residency.MEM_TRACKS) \
            == tracelib.track_band("residency")

    def test_contract_report_renders_every_section(self, capsys):
        assert cli.main(["--contract-report"]) == 0
        out = capsys.readouterr().out
        assert "contractlint report over" in out
        for section in ("gate keys (harness/regress.py SPECS",
                        "metric names consumed by string",
                        "RunLog record kinds",
                        "device-subtrack bands",
                        "chaos contract"):
            assert section in out
        # the live tree is burned down: every gate key has an
        # emitter and every string-consumed metric a producer. (The
        # record-kind section may show residue from deliberate test
        # fabrications — those carry rule-layer suppressions.)
        assert "MISSING EMITTER" not in out
        assert "MISSING PRODUCER" not in out
