"""Tests for the harness: timing protocol, verdict rules, run log.

The verdict rules are checked against the reference's arithmetic
(sycl_con.cpp:279-296, omp_con.cpp:223-244) with synthetic numbers, so
they hold regardless of host speed.
"""

import json
import time

import pytest

from hpc_patterns_tpu.harness import (
    RunLog,
    TimingResult,
    bandwidth_gbps,
    concurrency_verdict,
    correctness_verdict,
    measure,
)


def test_measure_min_of_reps():
    calls = []

    def fn():
        calls.append(time.perf_counter())
        time.sleep(0.001)

    r = measure(fn, repetitions=5, warmup=2)
    assert len(calls) == 7  # warmup excluded from samples
    assert len(r.times_s) == 5
    assert r.min_s <= r.mean_s <= r.max_s
    assert r.min_s >= 0.001


def test_measure_records_seq_stamped_rep_windows_under_trace():
    # with a flight recorder active, every TIMED repetition lands as a
    # device window carrying its seq index — the per-rank spans the
    # cross-rank merge matches (rank A's rep k vs rank B's rep k);
    # warmup reps stay off the device track
    from hpc_patterns_tpu.harness import metrics as metricslib
    from hpc_patterns_tpu.harness import trace as tracelib

    rec = tracelib.configure(enabled=True)
    try:
        measure(lambda: None, repetitions=3, warmup=2, label="unit.rep")
        wins = [ev for ev in rec.events
                if ev[0] == "X" and ev[1] == "device"
                and ev[2] == "unit.rep"]
        assert [w[6]["seq"] for w in wins] == [0, 1, 2]
    finally:
        tracelib.configure(enabled=False)
        metricslib.configure(enabled=False)


def test_timing_result_bandwidth():
    r = TimingResult((0.5, 1.0))
    assert r.bandwidth_gbps(1_000_000_000) == pytest.approx(2.0)
    assert bandwidth_gbps(10**9, 0) == float("inf")


def test_sycl_verdict_pass_and_fail():
    # two balanced commands, perfect overlap: speedup 2.0, theoretical 2.0
    v = concurrency_verdict([1.0, 1.0], 1.0, rule="sycl")
    assert v.success and v.speedup == pytest.approx(2.0)
    assert v.max_theoretical_speedup == pytest.approx(2.0)
    assert not v.warned_unbalanced
    # no overlap at all: speedup 1.0 < 2.0/1.3 -> FAILURE
    v = concurrency_verdict([1.0, 1.0], 2.0, rule="sycl")
    assert not v.success
    assert v.exit_code == 1
    # boundary: exactly theoretical/1.3 is NOT a pass (strict >)
    v = concurrency_verdict([1.0, 1.0], 1.3, rule="sycl")
    assert not v.success
    # just inside tolerance passes
    v = concurrency_verdict([1.0, 1.0], 1.29, rule="sycl")
    assert v.success


def test_sycl_verdict_unbalanced_warning():
    # one command dominates: theoretical = 1.1/1.0 = 1.1 <= 1.5 -> warn
    v = concurrency_verdict([1.0, 0.1], 1.0, rule="sycl")
    assert v.warned_unbalanced
    assert any("unbalanced" in m for m in v.messages)


def test_omp_verdict_rule():
    # PASS iff concurrent_total <= 1.3 * max_single (omp_con.cpp:238-244)
    assert concurrency_verdict([1.0, 1.0], 1.3, rule="omp").success
    assert not concurrency_verdict([1.0, 1.0], 1.31, rule="omp").success


def test_verdict_bad_inputs():
    with pytest.raises(ValueError):
        concurrency_verdict([], 1.0)
    with pytest.raises(ValueError):
        concurrency_verdict([1.0], 0.0)
    with pytest.raises(ValueError):
        concurrency_verdict([1.0], 1.0, rule="mystery")


def test_correctness_verdict():
    import numpy as np

    # the analytic oracle: sum of ranks 0..7 = 28 (allreduce-mpi-sycl.cpp:192-204)
    good = np.full(64, 28.0, dtype=np.float32)
    v = correctness_verdict(good, 28.0, rank=3)
    assert v.success
    assert "Passed 3" in v.messages[0]
    bad = good.copy()
    bad[17] = 27.0
    v = correctness_verdict(bad, 28.0, rank=0)
    assert not v.success
    assert "[17]" in v.messages[0]
    # integer dtype: exact equality required
    iv = np.full(8, 28, dtype=np.int32)
    assert correctness_verdict(iv, 28, dtype="int32").success
    iv[0] = 29
    assert not correctness_verdict(iv, 28, dtype="int32").success


def test_runlog_jsonl_and_summary(tmp_path, capsys):
    log = RunLog(tmp_path / "run.jsonl")
    v_ok = concurrency_verdict([1.0, 1.0], 1.0)
    v_bad = concurrency_verdict([1.0, 1.0], 2.0)
    log.result("a", v_ok, commands=["C", "M2D"])
    log.result("b", v_bad)
    ok, bad = log.summary()
    assert (ok, bad) == (1, 1)
    out = capsys.readouterr().out
    assert "SUCCESS count: 1" in out and "FAILURE count: 1" in out
    lines = [json.loads(l) for l in (tmp_path / "run.jsonl").read_text().splitlines()]
    assert [l["name"] for l in lines] == ["a", "b"]
    assert lines[0]["commands"] == ["C", "M2D"]


def test_resource_aware_verdict():
    from hpc_patterns_tpu.harness import concurrency_verdict

    # two commands on DIFFERENT resources: classic 2x bar (must overlap)
    v = concurrency_verdict([1.0, 1.0], 1.9, resources=["core", "hbm"])
    assert not v.success and v.max_theoretical_speedup == 2.0
    v = concurrency_verdict([1.0, 1.0], 1.05, resources=["core", "hbm"])
    assert v.success

    # two commands SHARING a resource: floor is the sum — no overlap is
    # physically possible, so ~1x passes and the 2x bar is never applied
    v = concurrency_verdict([1.0, 1.0], 2.1, resources=["hbm", "hbm"])
    assert v.success and v.max_theoretical_speedup == 1.0
    v = concurrency_verdict([1.0, 1.0], 2.8, resources=["hbm", "hbm"])
    assert not v.success  # >1.3x slower than the resource floor

    # misaligned resources rejected
    import pytest

    with pytest.raises(ValueError, match="align"):
        concurrency_verdict([1.0, 1.0], 1.0, resources=["core"])
