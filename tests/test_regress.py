"""Tier-1 smoke for the bench regression gate (harness/regress.py).

Runs over the REAL checked-in ``BENCH_r0*.json`` trajectory: the gate
must pass on history as it stands (r04/r05 are degenerate captures —
dead chip sessions — and must be skipped, not failed), and must fail
with a table naming the metric when the newest round is synthetically
degraded beyond tolerance. This is the machine check that keeps
``bench.py --gate`` honest without a chip.
"""

import glob
import json
import shutil
from pathlib import Path

import pytest

from hpc_patterns_tpu.harness import regress

REPO = Path(__file__).resolve().parent.parent
ROUNDS = sorted(glob.glob(str(REPO / "BENCH_r0*.json")))


@pytest.fixture()
def trajectory(tmp_path):
    """A scratch copy of the checked-in rounds (tests never mutate the
    real artifacts)."""
    paths = []
    for p in ROUNDS:
        dst = tmp_path / Path(p).name
        shutil.copy(p, dst)
        paths.append(str(dst))
    return paths


class TestCheckedInTrajectory:
    def test_rounds_exist(self):
        # the gate's acceptance claim is about the real files
        assert len(ROUNDS) >= 3

    def test_gate_passes_on_current_trajectory(self, capsys):
        assert regress.main(ROUNDS) == 0
        out = capsys.readouterr().out
        assert "GATE: PASS" in out
        # the degenerate rounds are skipped by name, not silently
        assert "skipped" in out

    def test_degenerate_rounds_are_skipped(self):
        recs = [regress.load_round(p) for p in ROUNDS]
        usable = [r for r in recs if regress.comparable(r)]
        skipped = [r for r in recs if not regress.comparable(r)]
        # r04 (parsed null) and r05 (detail.degenerate) must be out
        assert {r["n"] for r in skipped} >= {4, 5}
        assert all(isinstance(r["parsed"], dict) for r in usable)

    def test_synthetic_degradation_fails_naming_the_metric(
            self, trajectory, capsys):
        # degrade the newest COMPARABLE round's headline value beyond
        # tolerance; the gate must exit nonzero and name the metric
        recs = [(p, regress.load_round(p)) for p in trajectory]
        newest = max((pr for pr in recs if regress.comparable(pr[1])),
                     key=lambda pr: pr[1]["n"])
        path, rec = newest
        rec["parsed"]["value"] *= 0.7  # -30%, well past 10%
        rec.pop("_path")
        Path(path).write_text(json.dumps(rec))
        assert regress.main(trajectory) == 1
        out = capsys.readouterr().out
        assert "GATE: FAIL" in out
        assert "REGRESSION" in out
        assert "headline value" in out

    def test_dma_rate_is_informational_not_gated(self, capsys):
        # the checked-in r03 ran on a known ~11%-slow chip session
        # (dma 512.6 vs 579.5): session health must be REPORTED but
        # must not fail the gate — bench.py's own telemetry rule
        assert regress.main(ROUNDS) == 0
        out = capsys.readouterr().out
        assert "session health" in out
        assert "info" in out


class TestGateMechanics:
    def _round(self, tmp_path, n, value, vs_baseline=1.0, detail=None,
               parsed=True):
        rec = {"n": n, "cmd": "test", "rc": 0, "tail": ""}
        rec["parsed"] = (
            {"metric": "m", "value": value, "unit": "x",
             "vs_baseline": vs_baseline, "detail": detail or {}}
            if parsed else None)
        p = tmp_path / f"BENCH_r{n:02d}.json"
        p.write_text(json.dumps(rec))
        return str(p)

    def test_within_tolerance_passes(self, tmp_path, capsys):
        files = [self._round(tmp_path, 1, 2.0),
                 self._round(tmp_path, 2, 1.85)]  # -7.5% < 10%
        assert regress.main(files) == 0
        capsys.readouterr()

    def test_beyond_tolerance_fails(self, tmp_path, capsys):
        files = [self._round(tmp_path, 1, 2.0),
                 self._round(tmp_path, 2, 1.7)]  # -15%
        assert regress.main(files) == 1
        capsys.readouterr()

    def test_tolerance_flag(self, tmp_path, capsys):
        files = [self._round(tmp_path, 1, 2.0),
                 self._round(tmp_path, 2, 1.7)]
        assert regress.main(files + ["--tolerance", "0.2"]) == 0
        capsys.readouterr()

    def test_newest_degenerate_falls_back_to_prior(self, tmp_path,
                                                   capsys):
        files = [self._round(tmp_path, 1, 2.0),
                 self._round(tmp_path, 2, 1.95),
                 self._round(tmp_path, 3, 0.0,
                             detail={"degenerate": True})]
        # r3 measured nothing: r2 vs r1 is the comparison, and passes
        assert regress.main(files) == 0
        out = capsys.readouterr().out
        assert "r3" in out and "skipped" in out

    def test_improvement_against_best_not_last(self, tmp_path, capsys):
        # best prior is r1 (2.0), not the weaker r2: a slow newest
        # round must be judged against the trajectory's best
        files = [self._round(tmp_path, 1, 2.0),
                 self._round(tmp_path, 2, 1.0),
                 self._round(tmp_path, 3, 1.7)]
        assert regress.main(files) == 1
        capsys.readouterr()

    def test_lower_better_metric(self, tmp_path, capsys):
        files = [
            self._round(tmp_path, 1, 2.0,
                        detail={"serving_bubble_frac": 0.10}),
            self._round(tmp_path, 2, 2.0,
                        detail={"serving_bubble_frac": 0.30}),
        ]
        # 0.10 -> 0.30 is past 10% relative + 0.05 absolute slack
        assert regress.main(files) == 1
        out = capsys.readouterr().out
        assert "serving_bubble_frac" in out

    def test_backend_mismatch_gates_nothing(self, tmp_path, capsys):
        # a CPU-fallback capture must not "regress" against the TPU
        # trajectory — mismatched-backend priors are set aside
        files = [self._round(tmp_path, 1, 2.0,
                             detail={"backend": "tpu"}),
                 self._round(tmp_path, 2, 0.9,
                             detail={"backend": "cpu"})]
        assert regress.main(files) == 0
        out = capsys.readouterr().out
        assert "nothing to gate" in out

    def test_same_backend_still_gates(self, tmp_path, capsys):
        files = [self._round(tmp_path, 1, 2.0,
                             detail={"backend": "tpu"}),
                 self._round(tmp_path, 2, 0.9,
                             detail={"backend": "cpu"}),
                 self._round(tmp_path, 3, 1.5,
                             detail={"backend": "tpu"})]
        # r3 gates against r1 (tpu), r2 is set aside: -25% fails
        assert regress.main(files) == 1
        capsys.readouterr()

    def test_single_comparable_round_passes(self, tmp_path, capsys):
        files = [self._round(tmp_path, 1, 2.0),
                 self._round(tmp_path, 2, 0.0, parsed=False)]
        assert regress.main(files) == 0
        capsys.readouterr()

    def test_coverage_loss_warns_but_passes(self, tmp_path, capsys):
        # r1 carried serving_tok_s; r2 silently lost the measurement:
        # gate still exits 0 (the value didn't regress — it vanished)
        # but the loss is named on stdout AND stderr
        files = [self._round(tmp_path, 1, 2.0,
                             detail={"serving_tok_s": 100.0}),
                 self._round(tmp_path, 2, 2.0)]
        assert regress.main(files) == 0
        captured = capsys.readouterr()
        assert "coverage loss" in captured.out
        assert "serving_tok_s" in captured.out
        assert "r1" in captured.out
        assert "coverage loss" in captured.err

    def test_no_coverage_warning_when_keys_consistent(self, tmp_path,
                                                      capsys):
        files = [self._round(tmp_path, 1, 2.0,
                             detail={"serving_tok_s": 100.0}),
                 self._round(tmp_path, 2, 2.0,
                             detail={"serving_tok_s": 110.0})]
        assert regress.main(files) == 0
        captured = capsys.readouterr()
        assert "coverage loss" not in captured.out
        assert captured.err == ""

    def test_ungated_keys_never_flag_coverage_loss(self, tmp_path,
                                                   capsys):
        # dma_gbps is informational (session health): its absence is
        # not lost gate coverage
        files = [self._round(tmp_path, 1, 2.0,
                             detail={"dma_gbps": 500.0}),
                 self._round(tmp_path, 2, 2.0)]
        assert regress.main(files) == 0
        assert "coverage loss" not in capsys.readouterr().out

    def test_changed_headline_metric_is_not_coverage_loss(self, tmp_path,
                                                          capsys):
        # a round that switched headline metric is a different
        # trajectory (extract_metrics already refuses to compare it),
        # not a capture that lost keys
        r1 = {"n": 1, "cmd": "t", "rc": 0, "tail": "",
              "parsed": {"metric": "old_metric", "value": 2.0,
                         "vs_baseline": 1.0,
                         "detail": {"serving_tok_s": 100.0}}}
        r2 = {"n": 2, "cmd": "t", "rc": 0, "tail": "",
              "parsed": {"metric": "new_metric", "value": 2.0,
                         "vs_baseline": 1.0, "detail": {}}}
        files = []
        for rec in (r1, r2):
            p = tmp_path / f"BENCH_r{rec['n']:02d}.json"
            p.write_text(json.dumps(rec))
            files.append(str(p))
        assert regress.main(files) == 0
        assert "coverage loss" not in capsys.readouterr().out

    def test_checked_in_trajectory_has_no_coverage_loss(self, capsys):
        # the real BENCH_r0*.json history must not start warning —
        # the serving keys are wired but no checked-in round carries
        # them yet (ROADMAP), so nothing has been "lost"
        assert regress.main(ROUNDS) == 0
        assert "coverage loss" not in capsys.readouterr().out

    def test_unreadable_input_exits_2(self, tmp_path, capsys):
        bad = tmp_path / "nope.json"
        assert regress.main([str(bad)]) == 2
        capsys.readouterr()

    def test_bad_tolerance_exits_2(self, tmp_path, capsys):
        f = self._round(tmp_path, 1, 2.0)
        assert regress.main([f, "--tolerance", "1.5"]) == 2
        capsys.readouterr()


class TestQuantizedSpecs:
    def test_quantized_keys_are_gated_and_covered(self):
        # the round-13 gated keys exist, gate in the right direction,
        # and — being gated — ride the coverage-loss warning like
        # every other headline (a capture that silently drops
        # quant_goodput_tok_s warns instead of reading as green)
        by_path = {s.path: s for s in regress.SPECS}
        g = by_path["detail.quant_goodput_tok_s"]
        assert g.gated and g.direction == "higher"
        f = by_path["detail.kv_pool_bytes_frac"]
        assert f.gated and f.direction == "lower"
        assert f.abs_slack <= 0.05  # dtype geometry: tight band
        b = by_path["detail.quant_bubble_frac"]
        assert b.gated and b.direction == "lower"


class TestElasticSpecs:
    def test_elastic_keys_are_gated_and_covered(self):
        # the round-14 gated keys exist, gate in the right direction,
        # and — being gated — ride the coverage-loss warning like
        # every other headline (a capture that silently drops
        # elastic_slo_attainment warns instead of reading as green)
        by_path = {s.path: s for s in regress.SPECS}
        a = by_path["detail.elastic_slo_attainment"]
        assert a.gated and a.direction == "higher"
        assert a.abs_slack <= 0.05  # a fraction near 1.0: tight band
        g = by_path["detail.goodput_per_replica_round"]
        assert g.gated and g.direction == "higher"
        assert g.abs_slack == 0.0


class TestAutofitSpecs:
    def test_autofit_keys_are_gated_and_covered(self):
        # the round-16 gated keys exist, gate in the right direction,
        # and — being gated — ride the coverage-loss warning like
        # every other headline (a capture that silently drops
        # fitted_goodput_tok_s warns instead of reading as green)
        by_path = {s.path: s for s in regress.SPECS}
        g = by_path["detail.fitted_goodput_tok_s"]
        assert g.gated and g.direction == "higher"
        assert g.abs_slack == 0.0
        f = by_path["detail.autofit_gain_frac"]
        assert f.gated and f.direction == "higher"
        # the gain is a RATIO of two wall clocks: scheduler noise must
        # not fail the gate (a wrong fitter fails the row's own strict
        # padding assertion instead, surfacing as coverage loss here)
        assert f.abs_slack >= 0.03


class TestReqtraceSpecs:
    def test_reqtrace_keys_direction_and_gating(self):
        # round 18: coverage GATES (higher, tight band — a missing
        # stamp site leaks untracked time and regresses here); the p99
        # queue share is informational — where the tail went is
        # load-shape dependent, so it prints drift without failing
        # the gate
        by_path = {s.path: s for s in regress.SPECS}
        c = by_path["detail.attribution_coverage_frac"]
        assert c.gated and c.direction == "higher"
        assert c.abs_slack <= 0.02
        q = by_path["detail.ttft_p99_queue_share"]
        assert not q.gated and q.direction == "lower"


class TestStrictCoverage:
    _round = TestGateMechanics._round

    def test_default_mode_warns_and_passes(self, tmp_path, capsys):
        # without the flag, coverage loss stays a warning: exit 0,
        # WARNING on stderr (the pre-existing contract)
        files = [self._round(tmp_path, 1, 2.0,
                             detail={"serving_tok_s": 100.0}),
                 self._round(tmp_path, 2, 2.0)]
        assert regress.main(files) == 0
        captured = capsys.readouterr()
        assert "WARNING" in captured.err
        assert "coverage loss" in captured.err

    def test_strict_mode_fails_on_coverage_loss(self, tmp_path, capsys):
        # --strict-coverage turns the same loss into a failure: exit 1
        # with ERROR severity naming the key and the round that last
        # carried it
        files = [self._round(tmp_path, 1, 2.0,
                             detail={"serving_tok_s": 100.0}),
                 self._round(tmp_path, 2, 2.0)]
        assert regress.main(files + ["--strict-coverage"]) == 1
        captured = capsys.readouterr()
        assert "ERROR" in captured.err
        assert "serving_tok_s" in captured.err
        assert "r1" in captured.err

    def test_strict_mode_passes_when_coverage_holds(self, tmp_path,
                                                    capsys):
        files = [self._round(tmp_path, 1, 2.0,
                             detail={"serving_tok_s": 100.0}),
                 self._round(tmp_path, 2, 2.0,
                             detail={"serving_tok_s": 110.0})]
        assert regress.main(files + ["--strict-coverage"]) == 0
        assert capsys.readouterr().err == ""
