"""Known-bad: RunLog record-kind drift in both directions. A producer
writes ``engine_round`` records that no report/autofit path ever
dispatches on (and the kind is not declared forensic), and a consumer
dispatches on ``round_stats`` — the kind's old name — which nothing
writes anymore."""

FORENSIC_KINDS = ("engine_debug",)


def run_round(log, stats):
    # written every round, dispatched by nothing, not declared forensic
    log.emit(kind="engine_round", tok_s=stats["tok_s"])  # EXPECT: record-kind-drift
    log.emit(kind="engine_debug", raw=stats)


def summarize(records):
    # the producer renamed this kind to engine_round; the dispatch kept
    # the old name and now matches nothing
    rounds = [
        r
        for r in records
        if r.get("kind") == "round_stats"  # EXPECT: record-kind-drift
    ]
    return len(rounds)
