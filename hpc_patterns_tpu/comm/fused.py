"""Device-initiated fused ring collectives: Pallas remote-DMA kernels.

The host-driven :class:`~hpc_patterns_tpu.comm.communicator.Communicator`
paths dispatch a collective, wait for it, and only then run the
consumer — the reference repo's MPI shape. This module moves the ring
*into* the kernel: each step's neighbor transfer is a
``pltpu.make_async_remote_copy`` issued by the device itself, and the
local combine (the accumulate, the output write, the consuming matmul)
runs while the next transfer is in flight. The payoff Intel SHMEM
(arxiv 2409.20476) and DiOMP (2506.02486) measure for device-initiated
communication, on the TPU's ICI.

Every function here is **rank-local** (run inside ``shard_map``, like
:mod:`~hpc_patterns_tpu.comm.ring`); array-level entry points live on
the ``Communicator`` (``allreduce(algorithm="fused")``,
``allgather_matmul``, ``allreduce_into``), which keeps the host-driven
routes as the byte-exact oracles.

Kernel catalog:

- :func:`fused_allreduce` — two-phase ring allreduce (reduce-scatter +
  all-gather) in ONE kernel: the per-chunk accumulate happens in
  registers between the recv-wait and the next send, and the gather
  phase forwards each landing chunk onward *before* copying it into the
  output, so the forward hop rides under the output write. Chunk
  geometry and combine order mirror :func:`ring.ring_allreduce_chunked`
  exactly — the two are bitwise-equal, which is what the parity suite
  asserts.
- :func:`allreduce_into` — the same kernel with a fused epilogue: a
  bias add and/or an elementwise function applied to each reduced chunk
  AS IT LANDS (the reduction's consumer never sees a separate pass).
- :func:`allgather_matmul` — ring all-gather where every arriving shard
  immediately feeds a matmul tile against the local weight panel while
  the shard is simultaneously forwarded to the next neighbor — the
  dataflow ``parallel/ring_attention.py`` runs at the XLA level,
  dropped into a single kernel.
- :func:`fused_permute` / :func:`fused_ring_shift` — device-initiated
  ``lax.ppermute``: one remote DMA per rank, pair list validated by
  :func:`ring.check_permutation` (shardlint's ``unchecked-permutation``
  rule audits this entry point like it audits ``ppermute``).

Execution modes:

- **interpret** (default off-TPU): jax's dma-discharge interpreter maps
  each remote copy onto a lockstep ``all_gather`` + select, so the full
  dataflow — schedules, chunk indices, combines, epilogues — runs and
  is oracle-checked on the 8-device CPU mesh. Semaphores are inert
  arithmetic there, so the *synchronization protocol* (slot lifetimes,
  send-reuse waits, the drain discipline) is proven off-chip by the
  pallaslint semaphore ledger (``analysis/pallas_rules.py``, review
  time) and the strict-semaphore shim the parity battery runs under
  (``analysis/runtime.strict_semaphores``, trace time); what stays
  hardware-empirical is Mosaic's lowering and real DMA rates — the
  documented reground step.
- **compiled** (TPU): the same kernel lowered by Mosaic; neighbor ids
  ride ``DeviceIdType.LOGICAL`` scalars.

Multi-axis meshes: jax's dma-discharge rule (and the LOGICAL id space)
supports a single named mesh axis, so the kernels always run under a
shard_map binding ONE flat axis. A ring over one axis of a multi-axis
mesh is expressed as a :class:`RingGeometry` — the flat-id stride
between consecutive ring positions, computed from the mesh coordinates
(row-major device order, so axis ``i`` of sizes ``s`` has stride
``prod(s[i+1:])``). Every kernel takes ``geometry=`` and computes its
logical neighbor as ``flat_id + (next_pos - pos) * stride``; ranks that
share a ring position are replicas and run the identical schedule (the
parity suite pins their outputs bitwise-equal). The Communicator routes
multi-axis meshes through :func:`mesh_ring_geometry` / ``flat_mesh``
automatically — docs/comm.md walks the neighbor math.

VMEM footprint: the whole local shard plus ~2x its chunk working set
must fit VMEM (no grid streaming yet — benchmark shapes to ~MBs). The
wrapper pads the scatter axis to ``size * lane``-divisible width and
slices the pad back off; zero padding is combine-neutral for sum.
"""

from __future__ import annotations

import dataclasses
import math
from typing import Callable, Sequence

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from hpc_patterns_tpu.comm import ring
from hpc_patterns_tpu.ops.tiling import (
    collective_id as _registered_collective_id,
    default_interpret,
    tpu_compiler_params,
)

#: reduce ops the fused ring implements. ``prod`` is deliberately
#: absent: the host path's ``collectives._pprod`` is an all-gather+
#:  reduce FALLBACK (XLA has no native pprod), and silently routing
#: "fused prod" onto a sum-shaped ring would return wrong data, not
#: raise — see :func:`_check_op`.
FUSED_REDUCE_OPS = frozenset({"sum"})

#: chunk-width alignment on the compiled path (TPU lane width); 1 under
#: interpret so CPU parity shapes stay un-inflated
_TPU_LANE = 128

#: compiled-path VMEM budget: the whole local shard + two chunk-slot
#: arrays live in VMEM (no grid streaming yet), which passes Mosaic's
#: 16 MB default scoped limit at benchmark shapes; well under the
#: physical budget (the fused-MLP kernels use the same override)
_VMEM_LIMIT = 100 * 1024 * 1024

#: the single flat axis name every multi-axis routing binds (module
#: docstring): shard_map over ``flat_mesh(mesh)`` with this axis, ring
#: neighbors computed by :class:`RingGeometry` from mesh coordinates
FLAT_AXIS = "_fusedflat"


@dataclasses.dataclass(frozen=True)
class RingGeometry:
    """How one logical ring sits inside a flat device ordering.

    ``axis`` is the (single) mesh axis name the kernel's shard_map
    binds; ``size`` the ring length; ``stride`` the flat-id distance
    between consecutive ring positions; ``total`` the flat mesh size.
    The identity geometry (``stride=1, total=size``) is the classic
    1-D mesh and reproduces the original kernels' traces exactly; a
    multi-axis ring (from :func:`mesh_ring_geometry`) has
    ``total > size`` and every ``total // size`` flat ranks sharing a
    ring position compute identical (replicated) results."""

    axis: str
    size: int
    stride: int = 1
    total: int | None = None

    def __post_init__(self):
        if self.total is None:
            object.__setattr__(self, "total", self.size * self.stride)
        if self.size < 1 or self.stride < 1:
            raise ValueError(f"degenerate ring geometry: {self}")
        if self.total % (self.size * self.stride):
            raise ValueError(
                f"flat size {self.total} not divisible by "
                f"size*stride = {self.size * self.stride}: {self}")

    @property
    def identity(self) -> bool:
        return self.stride == 1 and self.total == self.size

    # -- in-kernel (traced) --------------------------------------------
    def me_and_right(self):
        """(ring position, right-neighbor FLAT id) — computed INSIDE
        the kernel body (a pallas kernel cannot capture traced values
        from the caller; ``lax.axis_index`` is legal in-kernel). The
        position indexes chunks; the flat id feeds ``device_id``."""
        me = lax.axis_index(self.axis)
        if self.identity:
            return me, lax.rem(me + 1, self.size)
        pos = lax.rem(me // self.stride, self.size)
        dst = me + (lax.rem(pos + 1, self.size) - pos) * self.stride
        return pos, dst

    def flat_index(self):
        """The rank's FLAT id (traced, in-kernel) — indexes per-rank
        SMEM tables like :func:`fused_permute`'s destination table."""
        return lax.axis_index(self.axis)

    # -- host-side (static) --------------------------------------------
    def positions(self) -> list[int]:
        """Ring position of every flat id — the take-index that expands
        a ``(size, ...)`` global array to its ``(total, ...)``
        replicated layout."""
        return [(f // self.stride) % self.size for f in range(self.total)]

    def ring_ids(self) -> list[int]:
        """One representative flat id per ring position (the fold-back
        selection after a flat-mesh collective)."""
        return [p * self.stride for p in range(self.size)]

    def flat_dst_table(self, dst_by_pos: Sequence[int]) -> list[int]:
        """Expand a position-level permutation destination table to
        flat ids: each flat rank sends to the SAME-replica rank of its
        position's destination."""
        out = []
        for f in range(self.total):
            pos = (f // self.stride) % self.size
            out.append(f + (int(dst_by_pos[pos]) - pos) * self.stride)
        return out


def mesh_ring_geometry(mesh, axis: str) -> RingGeometry:
    """The :class:`RingGeometry` of ring ``axis`` inside ``mesh``'s
    row-major flat device order: stride = product of the axis sizes to
    its RIGHT (``mesh.devices`` is C-ordered), bound under
    :data:`FLAT_AXIS` on :func:`flat_mesh`."""
    names = list(mesh.axis_names)
    if axis not in names:
        raise ValueError(f"axis {axis!r} not in mesh axes {names}")
    sizes = [int(mesh.shape[a]) for a in names]
    i = names.index(axis)
    stride = int(math.prod(sizes[i + 1:]))
    return RingGeometry(axis=FLAT_AXIS, size=sizes[i], stride=stride,
                        total=int(math.prod(sizes)))


def flat_mesh(mesh):
    """``mesh`` re-expressed as a 1-D mesh over :data:`FLAT_AXIS` in
    the same (row-major) device order — the mesh the multi-axis fused
    route shard_maps over."""
    from jax.sharding import Mesh

    return Mesh(mesh.devices.flatten(), (FLAT_AXIS,))


def _resolve_geometry(axis: str, geometry: RingGeometry | None, *,
                      shift: int = 1) -> RingGeometry:
    """Default (``geometry=None``) is the identity ring over ``axis``
    — the original single-axis behavior, ring size validated exactly
    like before. An explicit geometry carries a static size, so the
    same pair-list sanitizer runs on ring positions."""
    if geometry is None:
        return RingGeometry(axis=axis, size=_ring_size(axis, shift=shift))
    if geometry.axis != axis:
        raise ValueError(
            f"geometry axis {geometry.axis!r} != bound axis {axis!r}")
    ring.check_permutation(ring._ring_perm(geometry.size, shift),
                           geometry.size)
    return geometry


def _check_op(op: str) -> None:
    if op not in FUSED_REDUCE_OPS:
        raise ValueError(
            f"fused allreduce implements {sorted(FUSED_REDUCE_OPS)}, "
            f"got {op!r} — notably 'prod' must stay on the host path "
            "(collectives.allreduce op='prod'), whose all-gather "
            "fallback is the only exact route"
        )


def ring_layout(shape: Sequence[int], size: int, *,
                interpret: bool | None = None
                ) -> tuple[int, int, int, int]:
    """Chunk geometry shared by the kernels, their wrappers, and the
    parity tests: ``(m, n, cn, n_pad)`` for a local shard ``shape``
    flattened to ``(m, n)`` rows x cols. ``cn`` is the ring chunk
    width — ``ceil(n / size)`` rounded up to the lane multiple on the
    compiled path — and ``n_pad = size * cn`` is the padded column
    count the two-phase ring runs over. Tests build the byte-exact
    host oracle (``ring_allreduce_chunked`` over the padded array) from
    the same numbers, so wrapper and oracle can never disagree on
    geometry."""
    if interpret is None:
        interpret = default_interpret()
    shape = tuple(shape)
    if not shape:
        shape = (1,)
    n = shape[-1]
    m = math.prod(shape[:-1]) if len(shape) > 1 else 1
    lane = 1 if interpret else _TPU_LANE
    cn = max(1, -(-n // size))
    cn = -(-cn // lane) * lane
    return m, n, cn, size * cn


def _ring_size(axis: str, *, shift: int = 1) -> int:
    """Validated ring size: the static pair list is built and checked
    exactly like :func:`ring.ring_shift`'s — the deadlock/zero-fill
    sanitizer applies to the device-initiated ring the same as to
    ``ppermute``."""
    size = ring.axis_size(axis)
    perm = ring._ring_perm(size, shift)
    ring.check_permutation(perm, size)
    return size


def _remote_copy(src, dst, send_sem, recv_sem, device_id):
    """One device-initiated neighbor hop. Scalar LOGICAL ids: identical
    lowering on Mosaic (returned as-is) and under the dma-discharge
    interpreter (which rejects the tuple form)."""
    return pltpu.make_async_remote_copy(
        src_ref=src, dst_ref=dst, send_sem=send_sem, recv_sem=recv_sem,
        device_id=device_id,
        device_id_type=pltpu.DeviceIdType.LOGICAL,
    )


# ---------------------------------------------------------------------------
# fused_permute: device-initiated ppermute
# ---------------------------------------------------------------------------


def fused_permute(x, axis: str, perm, *, interpret: bool | None = None,
                  collective_id: int | None = None,
                  geometry: RingGeometry | None = None):
    """``lax.ppermute`` with the transfer issued by the device: rank
    ``s`` DMAs its shard straight into rank ``d``'s buffer for every
    ``(s, d)`` in ``perm``. The pair list passes
    :func:`ring.check_permutation` first (full permutation required —
    ppermute's silent zero-fill has no fused analog: every rank waits
    on exactly one incoming copy). ``collective_id``: kernels that may
    run CONCURRENTLY on chip (e.g. the K and V shifts of one
    ring-attention step) must carry distinct ids — same-id collective
    kernels share barrier state. Pass an id from
    :func:`ops.tiling.collective_id` (never a hand-picked integer —
    pallaslint flags magic ids); None takes this kernel's registered
    default. ``geometry``: a multi-axis ring (``perm`` is over ring
    POSITIONS; every replica rank of a position sends to the matching
    replica of the destination position)."""
    if collective_id is None:
        collective_id = _registered_collective_id("comm.fused.permute")
    g = (geometry if geometry is not None
         else RingGeometry(axis=axis, size=ring.axis_size(axis)))
    if g.axis != axis:
        raise ValueError(
            f"geometry axis {g.axis!r} != bound axis {axis!r}")
    size = g.size
    perm = [(int(s), int(d)) for s, d in perm]
    ring.check_permutation(perm, size)
    if interpret is None:
        interpret = default_interpret()
    if size == 1:
        return x
    dst_table = [0] * size
    for s, d in perm:
        dst_table[s] = d

    shape = x.shape
    x2 = x.reshape(max(1, math.prod(shape[:-1]) if len(shape) > 1 else 1),
                   shape[-1] if shape else 1)
    dsts = jnp.asarray(g.flat_dst_table(dst_table),
                       jnp.int32).reshape(g.total, 1)

    def kernel(dst_ref, x_ref, o_ref, send_sem, recv_sem):
        me = g.flat_index()
        dma = _remote_copy(x_ref, o_ref, send_sem, recv_sem,
                           dst_ref[me, 0])
        dma.start()
        dma.wait()

    out = pl.pallas_call(
        kernel,
        out_shape=jax.ShapeDtypeStruct(x2.shape, x2.dtype),
        in_specs=[pl.BlockSpec(memory_space=pltpu.SMEM),
                  pl.BlockSpec(memory_space=pltpu.VMEM)],
        out_specs=pl.BlockSpec(memory_space=pltpu.VMEM),
        scratch_shapes=[pltpu.SemaphoreType.DMA, pltpu.SemaphoreType.DMA],
        compiler_params=tpu_compiler_params(has_side_effects=True,
                                            collective_id=collective_id),
        interpret=interpret,
    )(dsts, x2)
    return out.reshape(shape)


def fused_ring_shift(x, axis: str, shift: int = 1, *,
                     interpret: bool | None = None,
                     collective_id: int | None = None,
                     geometry: RingGeometry | None = None):
    """Device-initiated :func:`ring.ring_shift`: rank r's shard lands on
    rank ``(r + shift) % size`` via one in-kernel remote DMA."""
    size = geometry.size if geometry is not None else ring.axis_size(axis)
    perm = ring._ring_perm(size, shift)
    ring.check_permutation(perm, size)
    return fused_permute(x, axis, perm, interpret=interpret,
                         collective_id=collective_id, geometry=geometry)


# ---------------------------------------------------------------------------
# fused_allreduce / allreduce_into: two-phase ring in one kernel
# ---------------------------------------------------------------------------


def _epilogue_write(o_ref, b_ref, epilogue, chunk_idx, cn, value):
    """out[:, chunk] = epilogue(value (+ bias chunk)) — the fused
    consumer applied as the chunk lands; elementwise, so chunkwise
    application equals whole-array application bit for bit."""
    if b_ref is not None:
        value = value + b_ref[:, pl.ds(chunk_idx * cn, cn)]
    if epilogue is not None:
        value = epilogue(value)
    # an epilogue computing in a wider dtype lands back in the
    # collective's dtype (the size==1 early exit matches)
    o_ref[:, pl.ds(chunk_idx * cn, cn)] = value.astype(o_ref.dtype)


def fused_allreduce(x, axis: str, *, op: str = "sum",
                    bias=None, epilogue: Callable | None = None,
                    interpret: bool | None = None,
                    geometry: RingGeometry | None = None):
    """Ring allreduce(sum) with the schedule run inside one Pallas
    kernel (module docstring). Rank-local: call inside ``shard_map``
    over ``axis``. Bitwise-equal to
    ``ring.ring_allreduce_chunked`` over the :func:`ring_layout`-padded
    array (the parity suite's oracle). ``bias``/``epilogue`` fuse a
    reduction consumer into the gather phase — see
    :func:`allreduce_into`. ``geometry``: run the ring over one axis of
    a multi-axis mesh (replica ranks reduce redundantly, bitwise-equal
    — the Communicator's multi-axis route)."""
    _check_op(op)
    if interpret is None:
        interpret = default_interpret()
    g = _resolve_geometry(axis, geometry)
    size = g.size
    shape = x.shape
    m, n, cn, n_pad = ring_layout(shape, size, interpret=interpret)
    if size == 1:
        # same dtype discipline as the kernel path: bias joins in x's
        # dtype, the epilogue's result lands back in it
        out = x if bias is None else x + jnp.asarray(bias, x.dtype)
        if epilogue is not None:
            out = epilogue(out)
        return out.astype(x.dtype)
    x2 = x.reshape(m, n)
    if n_pad != n:
        x2 = jnp.pad(x2, ((0, 0), (0, n_pad - n)))
    b2 = None
    if bias is not None:
        b2 = jnp.broadcast_to(jnp.asarray(bias, x.dtype),
                              shape).reshape(m, n)
        if n_pad != n:
            b2 = jnp.pad(b2, ((0, 0), (0, n_pad - n)))

    def kernel(*refs):
        if b2 is not None:
            x_ref, b_ref, o_ref = refs[:3]
            scratch = refs[3:]
        else:
            x_ref, o_ref = refs[:2]
            b_ref = None
            scratch = refs[2:]
        (rs_recv, sendbuf, ag_recv, rs_recv_sem, send_sem,
         ag_recv_sem, ag_send_sem) = scratch
        me, dst = g.me_and_right()

        def chunk(j):
            return x_ref[:, pl.ds(j * cn, cn)]

        # --- phase 1: ring reduce-scatter -------------------------------
        # identical chunk walk to ring.ring_reduce_scatter: send chunk
        # (me+size-1-s), accumulate the arriving partial as mine+incoming
        sendbuf[0] = chunk(lax.rem(me + size - 1, size))
        dmas = []
        d = _remote_copy(sendbuf.at[0], rs_recv.at[0],
                         send_sem.at[0], rs_recv_sem.at[0], dst)
        d.start()
        dmas.append(d)
        for s in range(1, size):
            dmas[s - 1].wait_recv()
            slot = s % 2
            if s >= 2:
                # the DMA that read this send buffer two steps ago must
                # have drained before the buffer is rewritten
                dmas[s - 2].wait_send()
            sendbuf[slot] = (chunk(lax.rem(me + size - 1 - s, size))
                             + rs_recv[s - 1])
            if s < size - 1:
                d = _remote_copy(sendbuf.at[slot], rs_recv.at[s],
                                 send_sem.at[slot], rs_recv_sem.at[s],
                                 dst)
                d.start()
                dmas.append(d)
        red_slot = (size - 1) % 2  # fully-reduced chunk ``me``

        # --- phase 2: ring all-gather, forward-before-write -------------
        # dedicated ag_recv slots, NOT rs_recv: a gather-phase write
        # into a reduce-scatter slot could land before the (slower)
        # neighbor's phase-1 read of it — nothing orders my phase-1
        # completion after the neighbor's consumption, only after its
        # step-0 send. Distinct buffers make the phases race-free.
        ag = _remote_copy(sendbuf.at[red_slot], ag_recv.at[0],
                          ag_send_sem.at[0], ag_recv_sem.at[0], dst)
        ag.start()
        ag_dmas = [ag]
        # own chunk written while the first hop flies
        _epilogue_write(o_ref, b_ref, epilogue, me, cn,
                        sendbuf[red_slot])
        for s in range(1, size):
            ag_dmas[s - 1].wait_recv()
            if s < size - 1:
                # forward the landing chunk onward FIRST; the output
                # write below then overlaps the in-flight hop
                d = _remote_copy(ag_recv.at[s - 1], ag_recv.at[s],
                                 ag_send_sem.at[s], ag_recv_sem.at[s],
                                 dst)
                d.start()
                ag_dmas.append(d)
            src = lax.rem(me + size - s, size)
            _epilogue_write(o_ref, b_ref, epilogue, src, cn,
                            ag_recv[s - 1])
        # no DMA may outlive the kernel's scratch. The loop already
        # consumed dmas[0..size-3]'s send sems (the slot-reuse waits);
        # only the LAST reduce-scatter send is still outstanding — a
        # second wait on a consumed sem would deadlock the compiled
        # kernel (one signal per DMA).
        dmas[-1].wait_send()
        for d in ag_dmas:
            d.wait_send()

    operands = [x2] if b2 is None else [x2, b2]
    out = pl.pallas_call(
        kernel,
        out_shape=jax.ShapeDtypeStruct((m, n_pad), x.dtype),
        in_specs=[pl.BlockSpec(memory_space=pltpu.VMEM)] * len(operands),
        out_specs=pl.BlockSpec(memory_space=pltpu.VMEM),
        scratch_shapes=[
            pltpu.VMEM((size - 1, m, cn), x.dtype),   # phase-1 recv slots
            pltpu.VMEM((2, m, cn), x.dtype),          # alternating sends
            pltpu.VMEM((size - 1, m, cn), x.dtype),   # phase-2 recv slots
            pltpu.SemaphoreType.DMA((size - 1,)),
            pltpu.SemaphoreType.DMA((2,)),
            pltpu.SemaphoreType.DMA((size - 1,)),
            pltpu.SemaphoreType.DMA((size - 1,)),
        ],
        compiler_params=tpu_compiler_params(
            has_side_effects=True,
            collective_id=_registered_collective_id(
                "comm.fused.allreduce"),
            vmem_limit_bytes=_VMEM_LIMIT),
        interpret=interpret,
    )(*operands)
    if n_pad != n:
        out = out[:, :n]
    return out.reshape(shape)


def allreduce_into(x, axis: str, *, bias=None,
                   epilogue: Callable | None = None,
                   interpret: bool | None = None,
                   geometry: RingGeometry | None = None):
    """Allreduce with its consumer fused into the gather phase: each
    reduced chunk gets ``epilogue(chunk + bias)`` applied AS THE DMA
    LANDS — the reduction's consumer (a bias add, an activation) costs
    no separate pass over the array. ``epilogue`` must be elementwise
    (chunkwise application is asserted byte-equal to whole-array
    application by the parity suite)."""
    return fused_allreduce(x, axis, bias=bias, epilogue=epilogue,
                           interpret=interpret, geometry=geometry)


# ---------------------------------------------------------------------------
# allgather_matmul: each arriving shard feeds the next matmul tile
# ---------------------------------------------------------------------------


def allgather_matmul(x, w, axis: str, *, interpret: bool | None = None,
                     geometry: RingGeometry | None = None):
    """``all_gather(x) @ w`` with the gather ring inside the kernel:
    at step ``s`` the shard that just arrived is forwarded to the next
    neighbor and THEN multiplied against the local weight panel — the
    matmul tile runs while the next shard is on the wire. Rank-local;
    ``x``: (m, k) rows shard, ``w``: (k, n) local panel; returns
    ``(size*m, n)`` with row-block ``j`` equal to rank j's
    ``x @ w`` — the ring-attention dataflow as one kernel."""
    if x.ndim != 2 or w.ndim != 2 or x.shape[1] != w.shape[0]:
        raise ValueError(
            f"allgather_matmul wants x (m, k) @ w (k, n), got "
            f"{x.shape} @ {w.shape}"
        )
    if interpret is None:
        interpret = default_interpret()
    g = _resolve_geometry(axis, geometry)
    size = g.size
    m, k = x.shape
    n = w.shape[1]
    if size == 1:
        return jnp.dot(x, w, preferred_element_type=jnp.float32
                       ).astype(x.dtype)

    def kernel(x_ref, w_ref, o_ref, buf, send_sem, recv_sem):
        me, dst = g.me_and_right()

        def tile(block, j):
            o_ref[pl.ds(j * m, m), :] = jnp.dot(
                block, w_ref[...], preferred_element_type=jnp.float32
            ).astype(o_ref.dtype)

        dmas = [_remote_copy(x_ref, buf.at[0], send_sem.at[0],
                             recv_sem.at[0], dst)]
        dmas[0].start()
        # local tile computes while the first shard flies
        tile(x_ref[...], me)
        for s in range(1, size):
            dmas[s - 1].wait_recv()
            if s < size - 1:
                d = _remote_copy(buf.at[s - 1], buf.at[s],
                                 send_sem.at[s], recv_sem.at[s], dst)
                d.start()
                dmas.append(d)
            # the arriving shard's tile overlaps the hop just started
            tile(buf[s - 1], lax.rem(me + size - s, size))
        for d in dmas:
            d.wait_send()

    return pl.pallas_call(
        kernel,
        out_shape=jax.ShapeDtypeStruct((size * m, n), x.dtype),
        in_specs=[pl.BlockSpec(memory_space=pltpu.VMEM)] * 2,
        out_specs=pl.BlockSpec(memory_space=pltpu.VMEM),
        scratch_shapes=[
            pltpu.VMEM((size - 1, m, k), x.dtype),
            pltpu.SemaphoreType.DMA((size - 1,)),
            pltpu.SemaphoreType.DMA((size - 1,)),
        ],
        compiler_params=tpu_compiler_params(
            has_side_effects=True,
            collective_id=_registered_collective_id(
                "comm.fused.allgather_matmul")),
        interpret=interpret,
    )(x, w)


def allgather_matmul_reference(x, w, axis: str):
    """The host-driven oracle for :func:`allgather_matmul`: XLA
    all-gather completes, THEN the tiles compute (no overlap), with the
    identical per-block dot shape/accumulation so the comparison is
    bitwise. Rank-local."""
    size = ring.axis_size(axis)
    gathered = lax.all_gather(x, axis, tiled=False)  # (size, m, k)
    blocks = [
        jnp.dot(lax.index_in_dim(gathered, j, keepdims=False), w,
                preferred_element_type=jnp.float32).astype(x.dtype)
        for j in range(size)
    ]
    return jnp.concatenate(blocks, axis=0)
