"""Point-to-point ping-pong: pt2pt latency/bandwidth between mesh pairs.

The BASELINE.json "2-rank device-buffer ping-pong" config, i.e. the
reference's paired blocking ``MPI_Send/MPI_Recv`` with even/odd ordering
(allreduce-mpi-sycl.cpp:50-58) run as a standalone benchmark. On TPU the
pair exchange is one ``lax.ppermute`` with the involution permutation
r ↔ r^1, riding ICI between mesh neighbors.

Sweeps message sizes ``--min-p .. -p`` (default 3..25, the 8 B–256 MiB
band of the BASELINE 8B–8GB axis that fits a dev box), reporting per-size
round-trip latency and per-rank bandwidth. Validation oracle: after two
exchanges every buffer is back home (ppermute with an involution applied
twice is the identity).
"""

from __future__ import annotations

import sys

import numpy as np

from hpc_patterns_tpu.apps import common
from hpc_patterns_tpu.comm.communicator import record_collective_bandwidth
from hpc_patterns_tpu.dtypes import get_traits
from hpc_patterns_tpu.harness import RunLog, Verdict, measure
from hpc_patterns_tpu.harness.cli import (
    add_msg_size_args,
    add_sweep_args,
    base_parser,
)
from hpc_patterns_tpu.harness.timing import blocking, max_across_processes


def build_parser():
    p = base_parser(__doc__.splitlines()[0])
    add_msg_size_args(p)
    add_sweep_args(p)
    p.add_argument("--world", type=int, default=-1, help="ranks; -1 = all devices")
    return p


def run(args) -> int:
    log = RunLog(args.log, truncate=not args.log_append)
    if args.min_p > args.log2_elements:
        # an empty sweep must not be a vacuous SUCCESS
        log.print(f"ERROR: --min-p {args.min_p} > -p {args.log2_elements}")
        log.print("FAILURE")
        return 1
    comm = common.make_communicator(args.backend, args.world, even=True)
    if comm.size < 2:
        log.print("SKIP: ping-pong needs >= 2 devices (even ranks, "
                  "allreduce-mpi-sycl.cpp:95-97)")
        log.print("SUCCESS")  # precondition skip, not a failure
        return 0
    traits = get_traits(args.dtype)
    all_ok = True
    for p in range(args.min_p, args.log2_elements + 1):
        n = 1 << p
        x = comm.rank_filled(n, traits.dtype)
        exchange = comm.jit_pingpong(x)
        result = measure(
            blocking(exchange, x), repetitions=args.repetitions,
            warmup=args.warmup, label="pingpong",
        )
        elapsed = max_across_processes(result.min_s)
        # validation: one hop moves rank r's data to r^1; rank_filled
        # makes row r the constant r, so the oracle is analytic and each
        # process checks only the rows it can address (multi-process
        # launches validate per rank, like the reference's per-rank
        # asserts)
        out = exchange(x)
        ok = all(
            bool(np.all(np.asarray(row) == (r ^ 1)))
            for r, row in common.local_rows(out)
        )
        ok = common.all_processes_agree(ok)
        all_ok &= ok
        nbytes = n * traits.itemsize
        record_collective_bandwidth("pingpong", nbytes, elapsed,
                                    latency_us=elapsed * 1e6)
        log.emit(
            kind="result",
            name=f"pingpong[p={p}]",
            success=ok,
            elements=n,
            bytes_per_rank=nbytes,
            latency_us=elapsed * 1e6,
            bandwidth_gbps=nbytes / elapsed / 1e9 if elapsed > 0 else float("inf"),
        )
        log.print(
            f"pingpong n=2^{p}: {elapsed * 1e6:.2f} us, "
            f"{nbytes / elapsed / 1e9:.3f} GB/s {'ok' if ok else 'MISMATCH'}"
        )
    verdict = Verdict(success=all_ok, messages=("SUCCESS" if all_ok else "FAILURE",))
    log.print(verdict.summary_line())
    return verdict.exit_code


def main(argv=None) -> int:
    return common.run_instrumented(run, build_parser().parse_args(argv))


if __name__ == "__main__":
    sys.exit(main())
