"""Known-clean: the dispatch/collect split the serving engine uses —
dispatch functions only ENQUEUE; readbacks live at the sync point."""

import numpy as np

from hpc_patterns_tpu.analysis import dispatch_critical


def _dispatch_chunk(engine):
    # dispatch-only: device ops enqueue, handles returned, no readback
    engine.pending = engine.step()
    count = int(engine.chunk)  # host-side bookkeeping: not a readback
    return engine.pending, count


@dispatch_critical
def enqueue_next(engine):
    engine.pending = engine.step()


def collect(engine):
    # NOT dispatch-critical: the readback is this function's whole job
    return np.asarray(engine.pending)
