"""jaxlint core: findings, suppressions, baseline, and the rule engine.

The analyzer's job is ahead-of-time hazard detection for the bug
classes this repo has actually paid for on hardware: the PR 2
"poisoned cache" was a zero-copy ``np.asarray`` host view of a buffer
a donated jit arg later mutated in place — statically detectable, and
only *diagnosable* after the fact by the flight recorder
(harness/trace.py). The reference suites are self-validating at RUN
time (every ``concurency/`` binary exits SUCCESS/FAILURE); jaxlint is
the same discipline moved to REVIEW time, the ahead-of-time hazard
checking the offloading-runtime literature leans on for device-memory
lifetime and ordering bugs (DiOMP-Offloading, Intel SHMEM — PAPERS.md).

Model:

- a :class:`Rule` inspects one parsed module (:class:`ModuleInfo`) and
  yields :class:`Finding`\\ s — ``file:line:col``, rule id, message,
  and a fix hint;
- ``# jaxlint: disable=<rule>[,<rule>]`` suppresses findings on its
  own line (trailing comment) or the next line (standalone comment).
  The rule name is MANDATORY and must be a registered rule: a bare or
  unknown ``disable`` is itself a finding (``bad-suppression``), so
  suppressions can't rot silently;
- a baseline file (``--baseline``) tolerates known findings by exact
  ``(path, rule, line)`` — the escape hatch for adopting the analyzer
  on a dirty tree. This repo's policy (ISSUE 4) is fix-or-suppress,
  so the shipped tree carries NO baseline;
- the driver walks ``*.py`` files, runs every registered rule, and
  partitions findings into live / suppressed / baselined.

Everything here is stdlib ``ast`` + ``tokenize``: the analyzer never
imports the code under analysis, so it runs in milliseconds and can't
be crashed (or biased) by import-time side effects.
"""

from __future__ import annotations

import ast
import io
import json
import re
import tokenize
from dataclasses import dataclass, field
from pathlib import Path
from typing import Callable, Iterable, Iterator

# Functions whose bodies are dispatch-critical (host-sync rule) when no
# @dispatch_critical marker is present: the serving engine's overlapped
# dispatch/admission path, and the eager collective completion helper.
# A host readback in any of these stalls the device queue the whole
# design exists to keep fed.
DEFAULT_DISPATCH_CRITICAL = frozenset({
    "_dispatch_chunk",
    "_dispatch_spec",
    "_admit",
    "_admit_row",
    "_try_admit",
    "_ready_in_span",
    # the round-8 robustness entry points: preemption decision/eviction,
    # shedding, and the admission high-water check all run inside the
    # serving loop at chunk boundaries — a stray host sync there stalls
    # the very pipeline preemption exists to keep fed (the one
    # DELIBERATE sync, the eviction snapshot, carries a justified
    # suppression in models/serving.py)
    "_maybe_preempt",
    "_preempt",
    "_shed_expired",
    "_queue_order",
    "_admissible",
    "_can_resume",
    # the round-10 serving-plane hot paths: one scheduler round, the
    # router's migration export/transfer dispatch, and the KV-handoff
    # install all run with (or behind) an in-flight decode chunk — a
    # stray host sync there exposes exactly the handoff latency the
    # plane exists to hide. The DELIBERATE syncs (the export snapshot,
    # the completion measurement closing a migration window) carry
    # justified suppressions in models/serving.py and
    # serving_plane/router.py.
    "service_round",
    "export_migration",
    "install_migration",
    "_dispatch_migration",
    "_install_pending",
    "_complete_migrations",
    # the round-11 tiered-memory paths: the residency manager's
    # prefetch/evict transfer pipeline and the serving engine's swap
    # machinery all run with (or ahead of) an in-flight decode chunk —
    # a stray host sync there serializes exactly the host<->HBM
    # latency the tier exists to hide. The DELIBERATE syncs (the
    # numpy-fallback host tier, the round-boundary window completions,
    # the swap-out cursor snapshot inside _detach_row) carry justified
    # suppressions in memory/residency.py and models/serving.py.
    "_dispatch_prefetch",
    "_install_prefetched",
    "_complete_prefetches",
    "_residency_balance",
    "_swap_out",
    "pull_payload",
    "push_payload",
    "_close_ripe_evicts",
    # the shared detach/attach primitives under export_migration /
    # install_migration / swap (round 11 refactor): the deliberate
    # chunk-boundary snapshot inside _detach_row carries the same
    # justified suppressions export_migration's body did before it
    # was hoisted
    "_detach_row",
    "_attach_row",
    # the round-12 prefix-sharing paths: the radix admission match,
    # the shared-page map/incref, the tail prefill, the decref release
    # funnel, and the cache reclaim all run inside the admission window
    # (with or behind an in-flight decode chunk) — they are HOST trie/
    # list work by design, and a stray device readback there (e.g.
    # reading cursors to "check" a match) stalls exactly the prefill
    # the cache exists to skip
    "_prefix_match",
    "_memo_match",
    "_request_need",
    "_insert_prefix",
    "_alloc_pages",
    "_incref_pages",
    "_decref_pages",
    "_reclaim_cache_pages",
    "_row_swappable",
    "_row_freeable_pages",
    # the round-13 quantized-decode paths: KV quantize/dequant and the
    # weight dequant accessor run INSIDE the traced step (pure jnp by
    # design), and the scale-pool write rides the same dispatch as the
    # page write — a host readback of a scale anywhere here (e.g.
    # float(scale.max()) to "sanity-check" a row before the write)
    # syncs the decode chunk on exactly the bytes quantization exists
    # to shrink
    "_quantize_rows",
    "_dequant",
    "_scale_write",
    "matmul_weight",
    # the round-14 elastic-plane paths: the scaling decision, the warm
    # spin-up, the drain's export loop, and death recovery all run at
    # the plane's round boundary with survivor chunks about to
    # dispatch — a stray host sync there stalls every replica's next
    # round behind one controller tick. The DELIBERATE syncs (the
    # spin-up's completion measurement, the checkpoint's round-
    # boundary key snapshot, the resume's host-list packing) carry
    # justified suppressions in serving_plane/autoscaler.py and
    # serving_plane/service.py.
    "_autoscale_round",
    "_spin_up",
    "_begin_drain",
    "_drain_step",
    "_kill_replica",
    "_recover_casualties",
    "_resume_request",
    "_route_again",
    "_checkpoint_replica",
    "_probe_replica_chaos",
    "_shed_request",
    # the round-16 autofit-apply paths: from_fitted constructors swap
    # in the fitted ladder/weights/thresholds right before serving
    # starts, and the per-round attainment gauge (_judge_window /
    # _emit_attainment) runs inside the router's service round with
    # replica chunks in flight — both must stay pure host dict/list
    # work; a device readback there would stall the very first chunks
    # the fitted config exists to speed up
    "from_fitted",
    "ladder_from",
    "_judge_window",
    "_emit_attainment",
    # the round-17 device-side migration paths: the fused DMA pair's
    # send dispatch and the recv-side landing check both run inside
    # the router's handoff window, behind the destination's in-flight
    # decode chunk — a host readback there (e.g. np.asarray of a page
    # slab to "verify" the copy) drags the payload back through the
    # host and forfeits exactly the device-to-device hop the tier
    # exists to buy. The transport resolution (_resolve_transport)
    # rides the same dispatch. (service.py's same-named socket
    # functions are pure host wire work and stay clean by
    # construction.)
    "send_migration",
    "recv_migration",
    "_resolve_transport",
    # the round-18 request-trace stamp paths: every lifecycle stamp
    # (harness/reqtrace.py) fires inside an engine or router
    # transition the batcher already owns — admission, preemption,
    # swap-out, migration export/install — with decode chunks in
    # flight. A stamp is a perf_counter read plus host list work by
    # contract; a device readback smuggled into one (np.asarray of
    # engine.pos to "enrich" a segment) turns the observability layer
    # itself into the tail it exists to explain.
    "begin_request",
    "stamp_transition",
    "finish_request",
    "export_history",
    "install_history",
    "restamp_submit",
})

# rule names are kebab-case identifiers; anything after the last name
# (the mandatory one-line justification, set off by any other char) is
# ignored by the parser but required by review convention
_SUPPRESS_RE = re.compile(
    r"#\s*jaxlint:\s*disable"
    r"(?:\s*=\s*(?P<rules>[A-Za-z0-9_-]+(?:\s*,\s*[A-Za-z0-9_-]+)*))?"
)


@dataclass(frozen=True)
class Finding:
    """One hazard: ``rule`` id, location, message, and a fix hint."""

    rule: str
    path: str
    line: int
    col: int
    message: str
    hint: str = ""

    @property
    def key(self) -> tuple[str, str, int]:
        """Baseline identity: exact (path, rule, line)."""
        return (self.path, self.rule, self.line)

    def format(self) -> str:
        loc = f"{self.path}:{self.line}:{self.col}"
        text = f"{loc}: {self.rule}: {self.message}"
        if self.hint:
            text += f"\n    hint: {self.hint}"
        return text


@dataclass
class ModuleInfo:
    """One parsed module plus the lookups every rule wants."""

    path: str
    source: str
    tree: ast.Module
    #: first-segment import aliases, e.g. {"np": "numpy",
    #: "jnp": "jax.numpy", "partial": "functools.partial"}
    aliases: dict[str, str] = field(default_factory=dict)
    #: child -> parent for every node (recompile rule needs it)
    parents: dict[ast.AST, ast.AST] = field(default_factory=dict)

    @classmethod
    def parse(cls, path: str | Path, source: str | None = None
              ) -> "ModuleInfo":
        path = str(path)
        if source is None:
            source = Path(path).read_text()
        tree = ast.parse(source, filename=path)
        info = cls(path=path, source=source, tree=tree)
        for node in ast.walk(tree):
            for child in ast.iter_child_nodes(node):
                info.parents[child] = node
            if isinstance(node, ast.Import):
                for a in node.names:
                    info.aliases[a.asname or a.name.split(".")[0]] = a.name
            elif isinstance(node, ast.ImportFrom) and node.module:
                for a in node.names:
                    info.aliases[a.asname or a.name] = (
                        f"{node.module}.{a.name}")
        return info

    def resolve(self, node: ast.AST) -> str | None:
        """Canonical dotted name of a Name/Attribute chain, with the
        first segment resolved through the module's import aliases —
        ``np.asarray`` -> ``numpy.asarray``, ``jnp.asarray`` ->
        ``jax.numpy.asarray`` — so rules match semantics, not spelling.
        None for anything that isn't a plain dotted chain."""
        parts: list[str] = []
        while isinstance(node, ast.Attribute):
            parts.append(node.attr)
            node = node.value
        if not isinstance(node, ast.Name):
            return None
        parts.append(node.id)
        parts.reverse()
        parts[0] = self.aliases.get(parts[0], parts[0])
        return ".".join(parts)


class Rule:
    """One hazard class. Subclasses set ``name``/``hint`` and implement
    :meth:`check` over a parsed module."""

    name: str = "?"
    #: one-line description for --list-rules and the docs catalog
    summary: str = ""
    #: rule family for --list-rules grouping: jaxlint (Python-level),
    #: shardlint (SPMD), pallaslint (in-kernel), contractlint
    #: (cross-module producer/consumer contracts)
    family: str = "jaxlint"
    hint: str = ""

    def check(self, mod: ModuleInfo, config: "AnalysisConfig"
              ) -> Iterable[Finding]:
        raise NotImplementedError

    def finding(self, mod: ModuleInfo, node: ast.AST, message: str,
                hint: str | None = None) -> Finding:
        return Finding(
            rule=self.name, path=mod.path,
            line=getattr(node, "lineno", 1),
            col=getattr(node, "col_offset", 0),
            message=message,
            hint=self.hint if hint is None else hint,
        )


@dataclass
class AnalysisConfig:
    """Tunables threaded to every rule."""

    dispatch_critical: frozenset[str] = DEFAULT_DISPATCH_CRITICAL
    #: rule names to run; None = all registered
    select: frozenset[str] | None = None


_REGISTRY: dict[str, Rule] = {}


def register(rule_cls: type[Rule]) -> type[Rule]:
    """Class decorator adding a rule to the global registry."""
    rule = rule_cls()
    if rule.name in _REGISTRY:
        raise ValueError(f"duplicate rule name {rule.name!r}")
    _REGISTRY[rule.name] = rule
    return rule_cls


def registered_rules() -> dict[str, Rule]:
    # rules.py / pallas_rules.py / contract_rules.py self-register on
    # import; import lazily so core stays importable without the rule
    # set (the runtime helper's case)
    from hpc_patterns_tpu.analysis import contract_rules  # noqa: F401
    from hpc_patterns_tpu.analysis import pallas_rules  # noqa: F401
    from hpc_patterns_tpu.analysis import rules  # noqa: F401

    return dict(_REGISTRY)


# -- suppressions ----------------------------------------------------------


def parse_suppressions(
    mod: ModuleInfo, known_rules: frozenset[str]
) -> tuple[dict[int, set[str]], list[Finding]]:
    """``# jaxlint: disable=<rule>``: {line: {rules}} plus the
    bad-suppression findings for bare/unknown forms. A trailing comment
    covers its own line; a standalone comment covers the next CODE line
    (justifications may continue over following comment lines)."""
    by_line: dict[int, set[str]] = {}
    bad: list[Finding] = []
    try:
        tokens = tokenize.generate_tokens(
            io.StringIO(mod.source).readline)
        comments = [t for t in tokens if t.type == tokenize.COMMENT]
    except tokenize.TokenError:  # pragma: no cover - ast parsed it
        return by_line, bad
    lines = mod.source.splitlines()
    for tok in comments:
        m = _SUPPRESS_RE.search(tok.string)
        if not m:
            continue
        line = tok.start[0]
        names = [r.strip() for r in (m.group("rules") or "").split(",")
                 if r.strip()]
        standalone = lines[line - 1][: tok.start[1]].strip() == ""
        target = line
        if standalone:
            target = line + 1
            while target <= len(lines) and (
                    not lines[target - 1].strip()
                    or lines[target - 1].lstrip().startswith("#")):
                target += 1
        if not names:
            bad.append(Finding(
                rule="bad-suppression", path=mod.path, line=line,
                col=tok.start[1],
                message="jaxlint: disable without a rule name",
                hint="name the rule: # jaxlint: disable=<rule> — blanket "
                     "suppressions hide new hazard classes",
            ))
            continue
        unknown = [n for n in names if n not in known_rules]
        for n in unknown:
            bad.append(Finding(
                rule="bad-suppression", path=mod.path, line=line,
                col=tok.start[1],
                message=f"jaxlint: disable of unknown rule {n!r}",
                hint="registered rules: "
                     + ", ".join(sorted(known_rules)),
            ))
        by_line.setdefault(target, set()).update(
            n for n in names if n in known_rules)
    return by_line, bad


# -- baseline --------------------------------------------------------------


def load_baseline(path: str | Path) -> set[tuple[str, str, int]]:
    """Known-finding keys from a baseline JSON (see
    :func:`write_baseline`)."""
    data = json.loads(Path(path).read_text())
    return {
        (f["path"], f["rule"], int(f["line"]))
        for f in data.get("findings", [])
    }


def write_baseline(path: str | Path, findings: list[Finding]) -> None:
    data = {
        "comment": "jaxlint baseline — tolerated findings by exact "
                   "(path, rule, line); regenerate with "
                   "--write-baseline. Repo policy is fix-or-suppress: "
                   "this file should stay empty or absent.",
        "findings": [
            {"path": f.path, "rule": f.rule, "line": f.line,
             "message": f.message}
            for f in findings
        ],
    }
    Path(path).write_text(json.dumps(data, indent=2) + "\n")


# -- driver ----------------------------------------------------------------


@dataclass
class Report:
    """One analysis run: live findings plus everything accounted away."""

    findings: list[Finding] = field(default_factory=list)
    suppressed: list[Finding] = field(default_factory=list)
    baselined: list[Finding] = field(default_factory=list)
    n_files: int = 0

    @property
    def ok(self) -> bool:
        return not self.findings

    def by_rule(self) -> dict[str, int]:
        counts: dict[str, int] = {}
        for f in self.findings:
            counts[f.rule] = counts.get(f.rule, 0) + 1
        return counts


def iter_python_files(paths: Iterable[str | Path]) -> Iterator[Path]:
    """``*.py`` under each path (a file is taken as-is), skipping
    ``__pycache__``/hidden dirs, in sorted order for stable output."""
    for p in paths:
        p = Path(p)
        if p.is_file():
            yield p
            continue
        for f in sorted(p.rglob("*.py")):
            if any(part.startswith((".", "__pycache__"))
                   for part in f.parts[len(p.parts):-1]):
                continue
            yield f


def analyze_file(
    path: str | Path,
    config: AnalysisConfig | None = None,
    rules: dict[str, Rule] | None = None,
) -> tuple[list[Finding], list[Finding]]:
    """(live, suppressed) findings for one file. Syntax errors become a
    single ``parse-error`` finding: an unparseable file is a file the
    analyzer is blind to, which CI must not read as clean."""
    config = config or AnalysisConfig()
    rules = rules if rules is not None else registered_rules()
    # suppression validity is judged against the FULL registry: running
    # a rule subset (--select) must not turn a valid suppression of an
    # unselected rule into a bad-suppression finding
    known = frozenset(rules) | {"parse-error"}
    if config.select is not None:
        rules = {k: v for k, v in rules.items() if k in config.select}
    try:
        mod = ModuleInfo.parse(path)
    except SyntaxError as e:
        return [Finding(
            rule="parse-error", path=str(path), line=e.lineno or 1,
            col=e.offset or 0, message=f"unparseable: {e.msg}",
            hint="jaxlint cannot vouch for a file it cannot parse",
        )], []
    suppress_map, bad = parse_suppressions(mod, known)
    raw: list[Finding] = list(bad)
    if config.select is not None:
        # hygiene findings respect the selection too (parse-error
        # always survives: a blind file is never a clean file)
        raw = [f for f in raw if f.rule in config.select]
    for rule in rules.values():
        raw.extend(rule.check(mod, config))
    live: list[Finding] = []
    suppressed: list[Finding] = []
    seen: set[tuple[str, int, int]] = set()
    for f in sorted(raw, key=lambda f: (f.line, f.col, f.rule)):
        # rules walking nested defs can visit a statement from both
        # the outer and the inner function — one hazard, one finding
        if (f.rule, f.line, f.col) in seen:
            continue
        seen.add((f.rule, f.line, f.col))
        # bad-suppression is never itself suppressible — the escape
        # hatch must not have an escape hatch
        if (f.rule != "bad-suppression"
                and f.rule in suppress_map.get(f.line, ())):
            suppressed.append(f)
        else:
            live.append(f)
    return live, suppressed


def run_paths(
    paths: Iterable[str | Path],
    config: AnalysisConfig | None = None,
    baseline: set[tuple[str, str, int]] | None = None,
) -> Report:
    """Analyze every file under ``paths``; the CLI's engine."""
    report = Report()
    rules = registered_rules()
    for f in iter_python_files(paths):
        live, suppressed = analyze_file(f, config, rules)
        report.n_files += 1
        report.suppressed.extend(suppressed)
        for finding in live:
            if baseline and finding.key in baseline:
                report.baselined.append(finding)
            else:
                report.findings.append(finding)
    return report
