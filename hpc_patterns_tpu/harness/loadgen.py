"""Open-loop load generation: seeded arrival processes for serving.

``bench_serving``'s original stream is CLOSED-loop: every request is
queued up front and a new one only makes progress when the engine frees
capacity — so the offered load adapts to the server and overload can
never happen. Real traffic is OPEN-loop: arrivals come on the *users'*
clock (the classic closed-vs-open distinction; under-provisioned
open-loop systems build queues and blow deadlines instead of politely
slowing the benchmark down). This module generates those arrival
schedules:

- **poisson** — memoryless arrivals at a constant mean rate (the
  steady-traffic null model);
- **bursty** — a two-phase Markov-modulated process: quiet periods at
  the base rate alternate with bursts at ``burst_factor`` times it
  (queue-depth spikes, the admission-control stressor);
- **diurnal** — a sinusoidally modulated rate (period ``period_s``,
  modulation depth ``depth``) sampled by thinning (peak-hour vs
  trough, the capacity-planning shape);
- **shared-prefix** (:func:`make_shared_prefix_schedule`, round 12) —
  any of the above arrival processes carrying shared-prefix STRUCTURE:
  template-pool prompts (K shared templates × per-request tails) and
  conversation-tree turns (a request extends an earlier request's
  prompt), the traffic the prefix-sharing KV arena serves
  (``models/serving.py`` ``prefix_cache=True``); token content comes
  from the one seeded rule :func:`materialize_prompt`.

Every schedule is DETERMINISTIC given its parameters and seed, and
round-trips through JSON (:meth:`Schedule.to_json`) — so a chaos run's
exact traffic can be replayed against a fix, and a scenario row in a
benchmark names the schedule that produced it.

Requests carry a **priority class** (:class:`PriorityClass`: lower
``priority`` number = more important, the P0/P1 convention) with
per-class SLO targets (consumed by ``harness/slo.py``) and an optional
queue ``deadline_s`` (consumed by the engine's shedding policy). The
serving engine admits in priority order and — with ``preempt=True`` —
evicts lower classes under page pressure (``models/serving.py``).

Import-light (numpy only): schedules must be buildable from jax-free
drivers and launcher children.
"""

from __future__ import annotations

import json
from dataclasses import asdict, dataclass, field
from typing import Any, Sequence

import numpy as np


@dataclass(frozen=True)
class PriorityClass:
    """One traffic class. ``priority``: lower = more important (the
    engine admits lower numbers first and may preempt higher ones for
    them). ``weight``: relative share of arrivals. ``ttft_slo_s`` /
    ``tpot_slo_s``: the class's SLO targets (None = no target —
    trivially attained). ``deadline_s``: queue-time shedding deadline
    (None = never shed)."""
    name: str
    priority: int
    weight: float = 1.0
    ttft_slo_s: float | None = None
    tpot_slo_s: float | None = None
    deadline_s: float | None = None


@dataclass(frozen=True)
class ScheduledRequest:
    """One arrival: WHEN it enters (``t_arrival_s``, relative to the
    run start), what class it belongs to, and its shape (prompt
    length, generation budget). Prompt token CONTENT is the driver's
    job (seeded separately) — the schedule is shape + timing only, so
    one schedule replays against any vocabulary.

    Shared-prefix STRUCTURE (round 12) rides as two optional fields:
    ``template`` (>= 0: this prompt = template ``template``'s tokens +
    a per-request tail) and ``parent`` (>= 0: a conversation-tree
    turn — this prompt = request ``parent``'s prompt + a tail, so
    prefixes grow down the tree). Still shape-only: the driver
    materializes tokens with :func:`materialize_prompt`, the ONE
    seeded content rule, so schedules stay vocabulary-agnostic and
    JSON-replayable."""
    index: int
    t_arrival_s: float
    cls: str
    priority: int
    prompt_len: int
    max_new: int
    deadline_s: float | None = None
    template: int = -1
    parent: int = -1


@dataclass(frozen=True)
class Schedule:
    """A replayable arrival schedule: the requests in arrival order
    plus the generating spec (provenance — a benchmark row can name
    exactly which traffic produced it)."""
    requests: tuple[ScheduledRequest, ...]
    spec: dict = field(default_factory=dict)

    @property
    def n(self) -> int:
        return len(self.requests)

    @property
    def duration_s(self) -> float:
        return self.requests[-1].t_arrival_s if self.requests else 0.0

    def to_json(self) -> str:
        return json.dumps({
            "spec": self.spec,
            "requests": [asdict(r) for r in self.requests],
        })

    @classmethod
    def from_json(cls, text: str) -> "Schedule":
        obj = json.loads(text)
        return cls(
            requests=tuple(ScheduledRequest(**r)
                           for r in obj.get("requests", [])),
            spec=dict(obj.get("spec", {})),
        )


# ---------------------------------------------------------------------------
# arrival processes (times only; all driven by one RandomState)
# ---------------------------------------------------------------------------


def poisson_times(n: int, rate_rps: float,
                  rng: np.random.RandomState) -> np.ndarray:
    """n arrival instants of a homogeneous Poisson process: cumulative
    exponential inter-arrivals at mean ``1/rate``."""
    if rate_rps <= 0:
        raise ValueError(f"rate_rps must be > 0, got {rate_rps}")
    return np.cumsum(rng.exponential(1.0 / rate_rps, size=n))


def bursty_times(n: int, rate_rps: float, rng: np.random.RandomState,
                 *, burst_factor: float = 8.0,
                 mean_quiet_s: float = 1.0,
                 mean_burst_s: float = 0.25) -> np.ndarray:
    """Two-phase modulated Poisson: exponential quiet phases at the
    base rate alternating with exponential burst phases at
    ``burst_factor``× it. The phase sequence and the arrivals inside
    each phase all come from ``rng`` — one seed, one schedule."""
    if burst_factor < 1.0:
        raise ValueError(f"burst_factor must be >= 1, got {burst_factor}")
    times: list[float] = []
    t = 0.0
    burst = False
    while len(times) < n:
        phase = rng.exponential(mean_burst_s if burst else mean_quiet_s)
        rate = rate_rps * (burst_factor if burst else 1.0)
        # arrivals inside this phase: sequential exponentials until the
        # phase ends (keeps the draw count deterministic per phase)
        u = t
        while True:
            u += rng.exponential(1.0 / rate)
            if u > t + phase or len(times) >= n:
                break
            times.append(u)
        t += phase
        burst = not burst
    return np.asarray(times[:n])


def diurnal_times(n: int, rate_rps: float, rng: np.random.RandomState,
                  *, period_s: float = 60.0,
                  depth: float = 0.8) -> np.ndarray:
    """Sinusoidally modulated Poisson sampled by thinning: the
    instantaneous rate is ``rate*(1 + depth*sin(2πt/period))``;
    candidates are generated at the peak rate and accepted with
    probability rate(t)/peak — the standard exact thinning
    construction, deterministic given ``rng``."""
    if not 0.0 <= depth < 1.0:
        raise ValueError(f"depth must be in [0, 1), got {depth}")
    peak = rate_rps * (1.0 + depth)
    times: list[float] = []
    t = 0.0
    while len(times) < n:
        t += rng.exponential(1.0 / peak)
        rate_t = rate_rps * (1.0 + depth * np.sin(2 * np.pi * t / period_s))
        if rng.uniform() * peak <= rate_t:
            times.append(t)
    return np.asarray(times)


_PROCESSES = {
    "poisson": poisson_times,
    "bursty": bursty_times,
    "diurnal": diurnal_times,
}


# ---------------------------------------------------------------------------
# schedule assembly
# ---------------------------------------------------------------------------


def _arrivals_and_classes(n: int, rate_rps: float,
                          classes: Sequence[PriorityClass],
                          process: str, seed: int, process_kw: dict):
    """Shared prologue of the schedule constructors: validate, pick
    the arrival process, seed the ONE RandomState, draw arrival times
    then per-request classes. The draw ORDER is part of the seeded
    contract — both constructors consume (times, classes) first, in
    this order, then continue with their own draws."""
    if n < 1:
        raise ValueError(f"n must be >= 1, got {n}")
    if not classes:
        raise ValueError("need at least one PriorityClass")
    gen = _PROCESSES.get(process)
    if gen is None:
        raise ValueError(f"unknown process {process!r} "
                         f"(known: {', '.join(sorted(_PROCESSES))})")
    rng = np.random.RandomState(seed)
    times = gen(n, rate_rps, rng, **process_kw)
    weights = np.asarray([c.weight for c in classes], np.float64)
    if weights.sum() <= 0:
        raise ValueError("class weights must sum > 0")
    weights = weights / weights.sum()
    cls_idx = rng.choice(len(classes), size=n, p=weights)
    return rng, times, cls_idx


def make_schedule(n: int, *, rate_rps: float,
                  classes: Sequence[PriorityClass],
                  prompt_lens: Sequence[int],
                  budgets: Sequence[int],
                  budget_probs: Sequence[float] | None = None,
                  process: str = "poisson", seed: int = 0,
                  **process_kw: Any) -> Schedule:
    """The one constructor: ``n`` arrivals from the named process, each
    assigned a class (by weight), a prompt length, and a budget — all
    from ONE seeded RandomState, so (params, seed) fully determine the
    schedule. ``process_kw`` passes through to the arrival process
    (``burst_factor``, ``period_s``, ...)."""
    rng, times, cls_idx = _arrivals_and_classes(
        n, rate_rps, classes, process, seed, process_kw)
    plens = rng.choice(np.asarray(prompt_lens, np.int64), size=n)
    budgets_arr = np.asarray(budgets, np.int64)
    probs = (np.asarray(budget_probs, np.float64)
             if budget_probs is not None else None)
    news = rng.choice(budgets_arr, size=n, p=probs)
    reqs = []
    for i in range(n):
        c = classes[int(cls_idx[i])]
        reqs.append(ScheduledRequest(
            index=i, t_arrival_s=float(times[i]), cls=c.name,
            priority=c.priority, prompt_len=int(plens[i]),
            max_new=int(news[i]), deadline_s=c.deadline_s))
    spec = {"process": process, "n": n, "rate_rps": rate_rps,
            "seed": seed, "prompt_lens": list(map(int, prompt_lens)),
            "budgets": list(map(int, budgets)),
            "classes": [asdict(c) for c in classes], **process_kw}
    return Schedule(requests=tuple(reqs), spec=spec)


def make_shared_prefix_schedule(
        n: int, *, rate_rps: float, classes: Sequence[PriorityClass],
        n_templates: int, template_len: int | Sequence[int],
        tail_lens: Sequence[int], budgets: Sequence[int],
        budget_probs: Sequence[float] | None = None,
        template_weights: Sequence[float] | None = None,
        tree_frac: float = 0.0, process: str = "poisson",
        seed: int = 0, **process_kw: Any) -> Schedule:
    """A SHARED-PREFIX arrival schedule — the traffic shape that makes
    a prefix-sharing KV arena earn its keep (models/serving.py's
    ``prefix_cache=True``): every prompt is a TEMPLATE (one of
    ``n_templates`` shared system-prompt/few-shot pools) plus a
    per-request tail, and with probability ``tree_frac`` a request is
    instead a CONVERSATION-TREE turn extending an earlier request's
    prompt by a tail — prefixes then grow down chains, the radix-tree
    shape. Arrival times come from the named process (Poisson/bursty/
    diurnal, like :func:`make_schedule`); everything — times, class,
    template, tail length, budget, parent — draws from ONE seeded
    RandomState, so (params, seed) fully determine the schedule and it
    JSON round-trips like every other process.

    ``template_len``: one length for all templates, or one per
    template. ``template_weights``: relative template popularity
    (default uniform — skew it to model a hot system prompt). The
    driver materializes token content with :func:`materialize_prompt`.
    """
    if n_templates < 1:
        raise ValueError(f"n_templates must be >= 1, got {n_templates}")
    if not 0.0 <= tree_frac <= 1.0:
        raise ValueError(f"tree_frac must be in [0, 1], got {tree_frac}")
    tlens = ([int(t) for t in template_len]
             if hasattr(template_len, "__len__")
             else [int(template_len)] * n_templates)
    if len(tlens) != n_templates or min(tlens) < 1:
        raise ValueError(
            f"template_len must be one positive length or one per "
            f"template, got {tlens} for {n_templates}")
    rng, times, cls_idx = _arrivals_and_classes(
        n, rate_rps, classes, process, seed, process_kw)
    tw = (np.asarray(template_weights, np.float64)
          if template_weights is not None
          else np.ones(n_templates, np.float64))
    if len(tw) != n_templates or tw.sum() <= 0:
        raise ValueError("template_weights must be one positive weight "
                         "per template")
    tmpl_idx = rng.choice(n_templates, size=n, p=tw / tw.sum())
    tails = rng.choice(np.asarray(tail_lens, np.int64), size=n)
    budgets_arr = np.asarray(budgets, np.int64)
    probs = (np.asarray(budget_probs, np.float64)
             if budget_probs is not None else None)
    news = rng.choice(budgets_arr, size=n, p=probs)
    tree_draw = rng.uniform(size=n)
    parent_pick = rng.randint(0, max(1, n), size=n)
    reqs: list[ScheduledRequest] = []
    plens: list[int] = []
    for i in range(n):
        c = classes[int(cls_idx[i])]
        tail = int(tails[i])
        if i > 0 and tree_draw[i] < tree_frac:
            # a follow-up turn: extend an EARLIER request's prompt —
            # the tree is over PROMPTS (deterministic lengths), the
            # documented modeling choice: response content would need
            # runtime feedback the schedule cannot carry
            parent = int(parent_pick[i]) % i
            plen = plens[parent] + tail
            template, par = -1, parent
        else:
            template = int(tmpl_idx[i])
            plen = tlens[template] + tail
            par = -1
        plens.append(plen)
        reqs.append(ScheduledRequest(
            index=i, t_arrival_s=float(times[i]), cls=c.name,
            priority=c.priority, prompt_len=plen, max_new=int(news[i]),
            deadline_s=c.deadline_s, template=template, parent=par))
    spec = {"process": process, "kind": "shared_prefix", "n": n,
            "rate_rps": rate_rps, "seed": seed,
            "n_templates": n_templates, "template_len": tlens,
            "tail_lens": list(map(int, tail_lens)),
            "budgets": list(map(int, budgets)),
            "tree_frac": tree_frac,
            "classes": [asdict(c) for c in classes], **process_kw}
    return Schedule(requests=tuple(reqs), spec=spec)


def materialize_prompt(schedule: Schedule, index: int, vocab: int,
                       *, seed: int | None = None) -> np.ndarray:
    """THE content rule for shared-prefix schedules: deterministic
    int32 tokens for request ``index`` — template tokens seeded by
    (seed, template id) so every request on a template shares the SAME
    prefix bytes, tails seeded by (seed, request index) so they
    diverge, and tree turns recursively extend their parent's prompt.
    One definition shared by drivers, benchmarks, and tests, so "the
    same schedule" always means the same tokens."""
    if vocab < 1:
        raise ValueError(f"vocab must be >= 1, got {vocab}")
    if seed is None:
        seed = int(schedule.spec.get("seed", 0))
    req = schedule.requests[index]
    tail_len = req.prompt_len - (
        schedule.requests[req.parent].prompt_len if req.parent >= 0
        else int(np.asarray(schedule.spec["template_len"])[req.template]))
    tail = np.random.RandomState(
        (seed * 1_000_003 + 7919 * (index + 1)) % (2 ** 31 - 1)
    ).randint(0, vocab, size=tail_len).astype(np.int32)
    if req.parent >= 0:
        head = materialize_prompt(schedule, req.parent, vocab, seed=seed)
    else:
        tlen = int(np.asarray(schedule.spec["template_len"])[req.template])
        head = np.random.RandomState(
            (seed * 1_000_003 + 104_729 * (req.template + 1))
            % (2 ** 31 - 1)
        ).randint(0, vocab, size=tlen).astype(np.int32)
    return np.concatenate([head, tail])


def staged_schedule(stages: Sequence[tuple[float, PriorityClass, int, int]],
                    spec: dict | None = None) -> Schedule:
    """An explicit hand-staged schedule — (t_arrival_s, class,
    prompt_len, max_new) tuples in arrival order. The deterministic
    building block for CI scenario smokes, where the preemption trigger
    must not depend on a random draw; still a :class:`Schedule`, so it
    serializes and replays exactly like a generated one."""
    reqs = []
    last = -np.inf
    for i, (t, c, plen, mnew) in enumerate(stages):
        if t < last:
            raise ValueError("staged arrivals must be non-decreasing")
        last = t
        reqs.append(ScheduledRequest(
            index=i, t_arrival_s=float(t), cls=c.name,
            priority=c.priority, prompt_len=int(plen),
            max_new=int(mnew), deadline_s=c.deadline_s))
    return Schedule(requests=tuple(reqs),
                    spec={"process": "staged", **(spec or {})})
