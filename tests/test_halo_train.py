"""Tests: halo exchange + stencil app, checkpoint/resume, trainer app."""

import numpy as np
import pytest

import jax

from hpc_patterns_tpu.topology import shard_map
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from hpc_patterns_tpu.comm import halo


class TestHaloExchange:
    def test_ghost_rows_match_neighbors(self, mesh8):
        n = 32  # 4 rows per rank
        x = jnp.arange(n, dtype=jnp.float32)
        padded = jax.jit(
            shard_map(
                lambda u: halo.halo_exchange(u, "x")[None],
                mesh=mesh8, in_specs=P("x"), out_specs=P("x", None),
            )
        )(x)
        padded = np.asarray(padded)  # (8, 6): halo+4+halo per rank
        for r in range(8):
            lo, hi = r * 4, (r + 1) * 4
            want = np.concatenate(
                [[(lo - 1) % n], np.arange(lo, hi), [hi % n]]
            ).astype(np.float32)
            np.testing.assert_array_equal(padded[r], want)

    def test_halo_validation(self, mesh8):
        with pytest.raises(ValueError, match="halo"):
            halo.halo_exchange(jnp.zeros((4, 2)), "x", halo=0)

    def test_stencil_app_passes(self, capsys):
        from hpc_patterns_tpu.apps import stencil_app

        code = stencil_app.main(
            ["-p", "10", "--steps", "8", "--repetitions", "1", "--warmup", "1"]
        )
        out = capsys.readouterr().out
        assert code == 0, out
        assert "SUCCESS" in out and "dense-match=True" in out


class TestCheckpoint:
    def test_roundtrip_sharded(self, tmp_path, mesh_dp_sp_tp):
        from hpc_patterns_tpu.models import TransformerConfig
        from hpc_patterns_tpu.models.train import init_train_state
        from hpc_patterns_tpu.utils.checkpoint import (
            latest_step,
            restore_checkpoint,
            save_checkpoint,
        )

        cfg = TransformerConfig(vocab=64, d_model=32, n_heads=8, n_layers=2,
                                d_ff=64, max_seq=32, attention="ring")
        params, opt = init_train_state(jax.random.PRNGKey(0), cfg, mesh_dp_sp_tp)
        save_checkpoint(tmp_path, params, opt, step=3)
        assert latest_step(tmp_path) == 3
        r_params, r_opt, step = restore_checkpoint(tmp_path, params, opt)
        assert step == 3
        a = np.asarray(jax.device_get(params["layers"]["wqkv"]))
        b = np.asarray(jax.device_get(r_params["layers"]["wqkv"]))
        np.testing.assert_array_equal(a, b)
        # restored arrays land sharded, same spec
        assert (
            r_params["layers"]["wqkv"].sharding.spec
            == params["layers"]["wqkv"].sharding.spec
        )

    def test_restore_missing(self, tmp_path):
        from hpc_patterns_tpu.utils.checkpoint import restore_checkpoint

        with pytest.raises(FileNotFoundError):
            restore_checkpoint(tmp_path / "nope", {}, {})


class TestTrainApp:
    def test_single_device_run(self, capsys):
        from hpc_patterns_tpu.apps import train_app

        code = train_app.main(
            ["--steps", "4", "--batch", "4", "--seq", "16", "--d-model", "32",
             "--n-layers", "1", "--n-heads", "4", "--vocab", "64"]
        )
        out = capsys.readouterr().out
        assert code == 0, out
        assert "SUCCESS" in out and "tok/s" in out

    @pytest.mark.slow  # unrolled-1F1B compile dominates (~1 min)
    def test_pp_run(self, capsys):
        from hpc_patterns_tpu.apps import train_app

        code = train_app.main(
            ["--steps", "3", "--batch", "4", "--seq", "8", "--d-model", "16",
             "--n-layers", "2", "--n-heads", "2", "--vocab", "32",
             "--pp", "2", "--microbatches", "2"]
        )
        out = capsys.readouterr().out
        assert code == 0, out
        assert "1f1b" in out and "SUCCESS" in out

    @pytest.mark.slow  # unrolled-1F1B compile dominates (~1 min)
    def test_pp_chunked_loss_run(self, capsys):
        # --pp x --loss-chunk trains: the pipeline loss head computes
        # the chunked (logits-free) NLL per microbatch
        from hpc_patterns_tpu.apps import train_app

        code = train_app.main(
            ["--steps", "3", "--batch", "4", "--seq", "8", "--d-model", "16",
             "--n-layers", "2", "--n-heads", "2", "--vocab", "32",
             "--pp", "2", "--microbatches", "2", "--loss-chunk", "8"]
        )
        out = capsys.readouterr().out
        assert code == 0, out
        assert "1f1b" in out and "SUCCESS" in out

    @pytest.mark.slow  # unrolled-1F1B compile dominates
    def test_pp_fsdp_run(self, capsys):
        # --pp x --fsdp: ZeRO-3 stage params through the 1F1B schedule
        from hpc_patterns_tpu.apps import train_app

        code = train_app.main(
            ["--steps", "3", "--batch", "4", "--seq", "8", "--d-model",
             "16", "--n-layers", "2", "--n-heads", "2", "--vocab", "32",
             "--pp", "2", "--fsdp", "2", "--microbatches", "2"]
        )
        out = capsys.readouterr().out
        assert code == 0, out
        assert "fsdp=2" in out and "SUCCESS" in out

    def test_pp_offload_opt_gated_on_cpu(self, capsys):
        # --pp x --offload-opt: composes (no rejection); on a CPU
        # backend the offload itself is gated with the same note as the
        # sharded-train path
        from hpc_patterns_tpu.apps import train_app

        code = train_app.main(
            ["--steps", "2", "--batch", "4", "--seq", "8", "--d-model",
             "16", "--n-layers", "2", "--n-heads", "2", "--vocab", "32",
             "--pp", "2", "--microbatches", "2", "--offload-opt"]
        )
        out = capsys.readouterr().out
        assert code == 0, out
        assert "ignoring" in out and "SUCCESS" in out

    def test_diverged_run_halts_early_and_fails(self, capsys, tmp_path):
        import os

        from hpc_patterns_tpu.apps import train_app

        code = train_app.main(
            ["--steps", "6", "--batch", "4", "--seq", "16", "--d-model",
             "32", "--n-layers", "1", "--n-heads", "4", "--vocab", "64",
             "--lr", "1e30", "--checkpoint-dir", str(tmp_path)]
        )
        out = capsys.readouterr().out
        assert code == 1
        assert "non-finite loss" in out and "halting early" in out
        assert "FAILURE" in out
        # a diverged run must never persist its NaN state
        assert not os.listdir(tmp_path)

    @pytest.mark.parametrize("dp,tp", [("2", "4"), ("-1", "2")])
    def test_dcn_dp_mesh(self, capsys, monkeypatch, dp, tp):
        # dp across synthetic slices, tp within one (make_hybrid_mesh);
        # the -1/tp=2 case uses only part of each slice, so the device
        # pick must be per-slice, never a flat prefix. Slices come from
        # the production env override (no monkeypatched grouping) — the
        # same protocol the cross-process launch test drives for real
        from hpc_patterns_tpu import topology
        from hpc_patterns_tpu.apps import train_app

        monkeypatch.setenv(topology.ENV_SLICE_GROUPING, "devices:4")
        code = train_app.main(
            ["--steps", "2", "--batch", "4", "--seq", "16", "--d-model",
             "32", "--n-layers", "1", "--n-heads", "4", "--vocab", "64",
             "--dp", dp, "--tp", tp, "--dcn-dp"]
        )
        out = capsys.readouterr().out
        assert code == 0, out
        assert "SUCCESS" in out

    def test_dcn_dp_guards(self, capsys):
        from hpc_patterns_tpu.apps import train_app

        # dp mismatched to the (single) slice count: clear error
        code = train_app.main(
            ["--steps", "1", "--batch", "2", "--seq", "16", "--d-model",
             "32", "--n-layers", "1", "--n-heads", "4", "--vocab", "64",
             "--dp", "2", "--tp", "4", "--dcn-dp"]
        )
        out = capsys.readouterr().out
        assert code == 1 and "slice count" in out
        # the same slice-count guard holds on the pp path (pp x dcn-dp
        # COMPOSES since round 4 — only the dp mismatch errors)
        assert train_app.main(["--pp", "2", "--dcn-dp", "--dp", "2",
                               "--n-layers", "2"]) == 1
        out = capsys.readouterr().out
        assert "slice count" in out

    def test_pp_rejects_sp_and_tp_moe(self, capsys):
        # --pp composes with --tp since round 5; sp/ep inside stages
        # and tp with MoE stages still reject
        from hpc_patterns_tpu.apps import train_app

        code = train_app.main(["--pp", "2", "--sp", "2"])
        out = capsys.readouterr().out
        assert code == 1
        assert "no sp/ep axes inside pipeline stages" in out
        code = train_app.main(["--pp", "2", "--tp", "2", "--n-experts",
                               "2"])
        out = capsys.readouterr().out
        assert code == 1
        assert "MoE" in out

    def test_pp_tp_trains(self, capsys):
        # Megatron tp inside pipeline stages through the CLI: loss
        # falls, SUCCESS verdict, tp in the run label
        from hpc_patterns_tpu.apps import train_app

        code = train_app.main(
            ["--backend", "cpu", "--pp", "2", "--tp", "2", "--steps", "3",
             "--batch", "4", "--seq", "16", "--d-model", "32",
             "--n-heads", "4", "--n-layers", "4", "--vocab", "64",
             "--microbatches", "2"]
        )
        out = capsys.readouterr().out
        assert code == 0, out
        assert "tp=2" in out and "SUCCESS" in out

    def test_mesh_run_with_resume(self, capsys, tmp_path):
        from hpc_patterns_tpu.apps import train_app

        code = train_app.main(
            ["--steps", "3", "--batch", "4", "--seq", "16", "--d-model", "32",
             "--n-layers", "1", "--n-heads", "8", "--vocab", "64",
             "--dp", "2", "--sp", "2", "--tp", "2", "--attention", "ring",
             "--resume-check", "--checkpoint-dir", str(tmp_path / "ck")]
        )
        out = capsys.readouterr().out
        assert code == 0, out
        assert "resume-check" in out and "SUCCESS" in out
