"""Serving plane (hpc_patterns_tpu/serving_plane/): the disaggregation
oracle and the router mechanics.

The load-bearing claim: a request routed prefill-replica →
KV-migration → decode-replica emits BYTE-IDENTICAL tokens to the same
request on a colocated single engine — greedy and sampled — because a
migrated request is structurally a resume on another replica (the
round-8 oracle machinery extended across engines). Everything else
(placement policies, per-replica accounting, ladder autotuning, the
wire codec) is pinned around that."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from hpc_patterns_tpu.models import TransformerConfig, init_params
from hpc_patterns_tpu.models.decode import paged_generate
from hpc_patterns_tpu.models.serving import (
    ContinuousBatcher,
    EngineCore,
    bucket_ladder,
    expected_padding,
    fit_bucket_ladder,
)
from hpc_patterns_tpu.serving_plane.migration import (
    bundle_from_wire,
    bundle_to_wire,
)
from hpc_patterns_tpu.serving_plane.router import Replica, ServingPlane

BASE = dict(vocab=64, d_model=32, n_heads=4, n_layers=2, d_ff=64,
            max_seq=64, dtype="float32")


def _setup(**over):
    cfg = TransformerConfig(**{**BASE, **over})
    params = init_params(jax.random.PRNGKey(0), cfg)
    return cfg, params


def _standalone(params, cfg, prompt, max_new, **kw):
    return np.asarray(paged_generate(
        params, jnp.asarray(prompt, jnp.int32)[None, :], cfg, max_new,
        page_size=8, **kw))[0]


def _requests(cfg, n, seed=1):
    rng = np.random.RandomState(seed)
    return [(rng.randint(0, cfg.vocab,
                         size=int(rng.choice([5, 8, 11])))
             .astype(np.int32),
             int(rng.choice([3, 6, 9]))) for _ in range(n)]


ENG = dict(slots=2, pool_pages=8, pages_per_seq=4, page_size=8,
           chunk=2)


class TestDisaggregationOracle:
    def test_prefill_migrate_decode_exact_greedy(self):
        # 1 prefill + 1 decode replica: every request crosses the KV
        # handoff, and every output must equal the colocated engine's
        cfg, params = _setup()
        plane = ServingPlane([
            Replica(EngineCore(params, cfg, **ENG), name="p",
                    role="prefill"),
            Replica(EngineCore(params, cfg, **ENG), name="d",
                    role="decode"),
        ])
        reqs = _requests(cfg, 5)
        ids = [plane.submit(p, m) for p, m in reqs]
        got = plane.run()
        assert sorted(got) == sorted(ids)
        assert plane.migrations >= 1
        for rid, (p, m) in zip(ids, reqs):
            np.testing.assert_array_equal(
                got[rid], _standalone(params, cfg, p, m),
                err_msg=f"rid {rid}")
        # both arenas drained back to empty
        for r in plane.replicas:
            assert sorted(r.engine.free_pages) == list(range(8))

    def test_prefill_migrate_decode_exact_sampled(self):
        # sampled mode: the migrated key state must continue the donor
        # row's stream exactly — same per-request key as standalone
        cfg, params = _setup()
        skw = dict(temperature=0.8, top_k=8, seed=0)
        plane = ServingPlane([
            Replica(EngineCore(params, cfg, **ENG, **skw), name="p",
                    role="prefill"),
            Replica(EngineCore(params, cfg, **ENG, **skw), name="d",
                    role="decode"),
        ])
        reqs = _requests(cfg, 4, seed=5)
        ids = [plane.submit(p, m) for p, m in reqs]
        got = plane.run()
        key_src = plane.replicas[0].engine
        for rid, (p, m) in zip(ids, reqs):
            want = _standalone(params, cfg, p, m,
                               key=key_src.request_key(rid),
                               temperature=0.8, top_k=8)
            np.testing.assert_array_equal(got[rid], want,
                                          err_msg=f"rid {rid}")

    def test_migrated_row_eos_still_truncates(self):
        # EOS state rides the migrated limit cursor: pick an eos id
        # from a standalone run's interior, serve through the plane
        cfg, params = _setup()
        prompt = np.arange(5, dtype=np.int32)
        full = _standalone(params, cfg, prompt, 9)
        eos = int(full[3])
        first = int(np.argmax(full == eos))
        plane = ServingPlane([
            Replica(EngineCore(params, cfg, **ENG, eos_id=eos),
                    name="p", role="prefill"),
            Replica(EngineCore(params, cfg, **ENG, eos_id=eos),
                    name="d", role="decode"),
        ])
        rid = plane.submit(prompt, 9)
        got = plane.run()[rid]
        np.testing.assert_array_equal(got, full[:first + 1])

    def test_open_loop_arrivals_through_the_plane(self):
        cfg, params = _setup()
        plane = ServingPlane([
            Replica(EngineCore(params, cfg, **ENG), name="p",
                    role="prefill"),
            Replica(EngineCore(params, cfg, **ENG), name="d",
                    role="decode"),
        ])
        reqs = _requests(cfg, 3, seed=9)
        arrivals = [(0.002 * i, dict(prompt=p, max_new=m))
                    for i, (p, m) in enumerate(reqs)]
        got = plane.run(arrivals=arrivals)
        assert sorted(got) == [0, 1, 2]
        for rid, (p, m) in zip(range(3), reqs):
            np.testing.assert_array_equal(
                got[rid], _standalone(params, cfg, p, m))


class TestRouterMechanics:
    def test_homogeneous_round_robin_spreads_and_stays_exact(self):
        cfg, params = _setup()
        plane = ServingPlane(
            [Replica(EngineCore(params, cfg, **ENG), name=f"r{i}")
             for i in range(2)],
            policy="round_robin")
        reqs = _requests(cfg, 4, seed=3)
        ids = [plane.submit(p, m) for p, m in reqs]
        got = plane.run()
        assert {plane.stats[r]["replica"] for r in ids} == {"r0", "r1"}
        for rid, (p, m) in zip(ids, reqs):
            np.testing.assert_array_equal(
                got[rid], _standalone(params, cfg, p, m))

    def test_least_loaded_prefers_free_pages(self):
        cfg, params = _setup()
        big = Replica(EngineCore(params, cfg, slots=2, pool_pages=12,
                                 pages_per_seq=4, page_size=8,
                                 chunk=2), name="big")
        small = Replica(EngineCore(params, cfg, **ENG), name="small")
        plane = ServingPlane([small, big], policy="least_loaded")
        rid = plane.submit(np.arange(5, dtype=np.int32), 3)
        assert plane.stats[rid]["replica"] == "big"
        plane.run()

    def test_plane_slo_rollup_spans_replicas(self):
        from hpc_patterns_tpu.harness import slo as slolib

        cfg, params = _setup()
        plane = ServingPlane(
            [Replica(EngineCore(params, cfg, **ENG), name="p",
                     role="prefill"),
             Replica(EngineCore(params, cfg, **ENG), name="d",
                     role="decode")],
            slo={0: slolib.SLOTarget()})
        reqs = _requests(cfg, 3, seed=11)
        for p, m in reqs:
            plane.submit(p, m)
        plane.run()
        tot = plane.last_slo["total"]
        assert tot["n"] == 3 and tot["served"] == 3
        assert tot["tokens"] == sum(m for _, m in reqs)
        assert tot["goodput_tok_s"] == tot["tok_s"] > 0
        # migrated requests are judged once, end to end: t_first came
        # from the prefill replica, t_finish from the decode replica
        for rec in plane.stats.values():
            assert rec["t_first"] is not None
            assert rec["t_finish"] >= rec["t_first"]

    def test_validation_guards(self):
        from hpc_patterns_tpu.harness import slo as slolib  # noqa: F401

        cfg, params = _setup()
        mk = lambda **kw: EngineCore(params, cfg, **ENG, **kw)
        with pytest.raises(ValueError, match="unique"):
            ServingPlane([Replica(mk(), name="x"),
                          Replica(mk(), name="x")])
        with pytest.raises(ValueError, match="policy"):
            ServingPlane([Replica(mk())], policy="nope")
        with pytest.raises(ValueError, match="disagrees on"):
            ServingPlane([Replica(mk(), name="a"),
                          Replica(mk(temperature=0.5), name="b")])
        with pytest.raises(ValueError, match="different"):
            ServingPlane([
                Replica(mk(temperature=0.5), name="a"),
                Replica(mk(temperature=0.5, seed=1), name="b")])
        with pytest.raises(ValueError, match="decode-capable"):
            ServingPlane([Replica(mk(), role="prefill")])
        with pytest.raises(ValueError, match="no live replica"):
            plane = ServingPlane([Replica(mk(), name="a")])
            plane.submit(np.arange(40, dtype=np.int32), 30)

    def test_submit_rejects_rows_no_decode_replica_can_hold(self):
        # a prefill-routed row LEAVES via migration: if no decode
        # replica's table can hold its pages, submit must reject it
        # up front instead of parking it forever (the mid-stream
        # plane-deadlock shape)
        cfg, params = _setup()
        plane = ServingPlane([
            Replica(EngineCore(params, cfg, **ENG), name="p",
                    role="prefill"),
            Replica(EngineCore(params, cfg, slots=2, pool_pages=4,
                               pages_per_seq=2, page_size=8, chunk=2),
                    name="d", role="decode"),
        ])
        with pytest.raises(ValueError, match="decode-capable"):
            plane.submit(np.arange(10, dtype=np.int32), 10)  # 3 pages
        # a row that fits both sides still serves end to end
        rid = plane.submit(np.arange(5, dtype=np.int32), 3)
        got = plane.run()
        np.testing.assert_array_equal(
            got[rid],
            _standalone(params, cfg, np.arange(5, dtype=np.int32), 3))


def _pinned_plane(cfg, params, migration, eng_kw=None, n_reqs=4,
                  seed=1):
    """1 prefill + 1 decode replica pinned to DISTINCT devices (the
    multi-chip serving shape on the CPU mesh) with the requested
    KV-handoff transport, plus the request list they'll serve."""
    d = jax.devices()[:2]
    replicas = []
    for i, role in enumerate(("prefill", "decode")):
        with jax.default_device(d[i]):
            p = jax.device_put(params, d[i])
            eng = EngineCore(p, cfg, **{**ENG, **(eng_kw or {})})
        replicas.append(Replica(eng, name=role[0], role=role,
                                device=d[i]))
    return (ServingPlane(replicas, migration=migration),
            _requests(cfg, n_reqs, seed=seed))


class TestDmaMigration:
    """The round-17 transport tier: ``ServingPlane(migration="dma")``
    routes every KV handoff over the fused paired remote-DMA kernel
    (comm/migration_dma.py) — and must stay byte-exact vs the
    colocated engine AND vs the wire-codec path, greedy and sampled,
    at every pool dtype, with the DMA ledger proving no silent
    fallback impersonated the kernel route."""

    @pytest.mark.parametrize(
        "over", [{}, {"dtype": "bfloat16"},
                 {"kv_cache_dtype": "int8"}, {"kv_cache_dtype": "fp8"}],
        ids=["f32", "bf16", "int8", "fp8"])
    def test_dma_plane_exact_greedy_every_pool_dtype(self, over):
        cfg, params = _setup(**over)
        plane, reqs = _pinned_plane(cfg, params, "dma")
        ids = [plane.submit(p, m) for p, m in reqs]
        got = plane.run()
        # every handoff rode the kernel — the transports Counter and
        # the DMA-only overlap ledger both say so
        assert plane.migration_transports["dma"] == len(reqs)
        assert sum(plane.migration_transports.values()) == len(reqs)
        assert plane.last_dma_migration_overlap_frac is not None
        assert plane.migration_bytes_per_round > 0
        for rid, (p, m) in zip(ids, reqs):
            np.testing.assert_array_equal(
                got[rid], _standalone(params, cfg, p, m),
                err_msg=f"rid {rid}")

    def test_dma_plane_exact_sampled(self):
        cfg, params = _setup()
        skw = dict(temperature=0.8, top_k=8, seed=0)
        plane, reqs = _pinned_plane(cfg, params, "dma", eng_kw=skw,
                                    seed=5)
        ids = [plane.submit(p, m) for p, m in reqs]
        got = plane.run()
        assert plane.migration_transports["dma"] == len(reqs)
        key_src = plane.replicas[0].engine
        for rid, (p, m) in zip(ids, reqs):
            want = _standalone(params, cfg, p, m,
                               key=key_src.request_key(rid),
                               temperature=0.8, top_k=8)
            np.testing.assert_array_equal(got[rid], want,
                                          err_msg=f"rid {rid}")

    def test_dma_matches_wire_path(self):
        # the two extreme transports (device-side kernel vs byte
        # codec) must agree token for token on the same stream
        cfg, params = _setup()
        outs = {}
        for mig in ("dma", "wire"):
            plane, reqs = _pinned_plane(cfg, params, mig, seed=3)
            ids = [plane.submit(p, m) for p, m in reqs]
            got = plane.run()
            assert plane.migration_transports[mig] == len(reqs)
            outs[mig] = [got[r] for r in ids]
        for i, (a, b) in enumerate(zip(outs["dma"], outs["wire"])):
            np.testing.assert_array_equal(a, b, err_msg=f"req {i}")

    def test_schedule_chain_fingerprints_resolved_transport(self):
        # the CollectiveSchedule's kv_migration entries carry the
        # RESOLVED algorithm — a fallback is visible in the chain,
        # not just the logs
        from hpc_patterns_tpu.analysis import runtime as art
        from hpc_patterns_tpu.harness import trace as tracelib

        cfg, params = _setup()
        tracelib.configure(enabled=True)  # fresh recorder + chain
        try:
            plane, reqs = _pinned_plane(cfg, params, "dma", n_reqs=2)
            ids = [plane.submit(p, m) for p, m in reqs]
            plane.run()
            algos = [e.get("algorithm") for e in art._schedule.entries
                     if e["op"] == "kv_migration"]
        finally:
            # also resets the chain — read the entries BEFORE this
            tracelib.configure(enabled=False)
        assert algos and set(algos) == {"dma"}

    def test_fallback_to_device_put_is_loud(self):
        # device-less (host-shared) replicas cannot serve DMA: the
        # plane still serves exactly, but warns, counts the fallback,
        # and reports NO dma overlap number (None, not a value
        # measured on the wrong transport)
        cfg, params = _setup()
        plane = ServingPlane([
            Replica(EngineCore(params, cfg, **ENG), name="p",
                    role="prefill"),
            Replica(EngineCore(params, cfg, **ENG), name="d",
                    role="decode"),
        ], migration="dma")
        reqs = _requests(cfg, 3)
        ids = [plane.submit(p, m) for p, m in reqs]
        with pytest.warns(RuntimeWarning, match="fell back"):
            got = plane.run()
        assert plane.migration_transports["dma"] == 0
        assert plane.last_dma_migration_overlap_frac is None
        for rid, (p, m) in zip(ids, reqs):
            np.testing.assert_array_equal(
                got[rid], _standalone(params, cfg, p, m))

    def test_unknown_transport_rejected(self):
        cfg, params = _setup()
        with pytest.raises(ValueError, match="migration transport"):
            ServingPlane([Replica(EngineCore(params, cfg, **ENG))],
                         migration="carrier-pigeon")


class TestReplicaDeathStaticPlane:
    """The FIXED plane's degraded mode under ``die:replica=N`` chaos
    (the in-process ``replica_round`` site): a death ends in SHEDDING
    — counted in the SLO table and ``shed_on_death``, never silent —
    which is exactly the baseline the elastic plane
    (serving_plane/autoscaler.py, tests/test_autoscaler.py) beats."""

    def test_death_sheds_counted_survivors_stay_exact(self):
        from hpc_patterns_tpu.harness import chaos as chaoslib
        from hpc_patterns_tpu.harness import slo as slolib

        cfg, params = _setup()
        reqs = _requests(cfg, 4, seed=21)
        chaoslib.configure("die:replica=1,at=1,site=replica_round")
        try:
            plane = ServingPlane(
                [Replica(EngineCore(params, cfg, **ENG), name=f"r{i}")
                 for i in range(2)],
                slo={0: slolib.SLOTarget()})
            ids = [plane.submit(p, m) for p, m in reqs]
            got = plane.run()
            died = [e for e in chaoslib.injections()
                    if e["kind"] == "die"]
        finally:
            chaoslib.reset()
        # the fault fired against the replica ORDINAL and was logged
        assert died and died[0]["rank"] == 1
        assert plane.deaths == ["r1"]
        # every request resolved: the dead replica's rows are SHED
        # (empty output, outcome in the table), the survivor's stay
        # byte-exact — nothing dropped silently
        assert plane.shed_on_death >= 1
        outcomes = {plane.stats[r]["outcome"] for r in ids}
        assert outcomes == {"ok", "shed"}
        for rid, (p, m) in zip(ids, reqs):
            if plane.stats[rid]["outcome"] == "ok":
                np.testing.assert_array_equal(
                    got[rid], _standalone(params, cfg, p, m))
            else:
                assert len(got[rid]) == 0
        # attainment shows the damage: shed never attains
        tot = plane.last_slo["total"]
        assert tot["shed"] == plane.shed_on_death
        assert tot["attained_frac"] < 1.0


class TestMigrationPrimitives:
    def test_export_install_guards(self):
        cfg, params = _setup()
        src = EngineCore(params, cfg, **ENG)
        dst = EngineCore(params, cfg, **{**ENG, "page_size": 16})
        src.submit(np.arange(5, dtype=np.int32), 4)
        src.service_round(decode=False)
        [slot] = src.exportable_slots()
        b = src.export_migration(slot)
        with pytest.raises(ValueError, match="page_size"):
            dst.install_migration(b)
        with pytest.raises(ValueError, match="no exportable row"):
            src.export_migration(slot)  # already released

    def test_migrated_seq_id_collision_refused(self):
        cfg, params = _setup()
        src = EngineCore(params, cfg, **ENG)
        dst = EngineCore(params, cfg, **ENG)
        src.submit(np.arange(5, dtype=np.int32), 4, seq_id=7)
        dst.submit(np.arange(5, dtype=np.int32), 4, seq_id=7)
        src.service_round(decode=False)
        b = src.export_migration(src.exportable_slots()[0])
        with pytest.raises(ValueError, match="already known"):
            dst.install_migration(b)

    def test_wire_codec_roundtrips_bit_identical(self):
        cfg, params = _setup()
        src = EngineCore(params, cfg, **ENG, temperature=0.7, seed=0)
        dst = EngineCore(params, cfg, **ENG, temperature=0.7, seed=0)
        prompt = np.arange(6, dtype=np.int32)
        src.submit(prompt, 5)
        src.service_round(decode=False)
        b = src.export_migration(src.exportable_slots()[0])
        b.seq = 3
        wire = bundle_to_wire(b)
        b2 = bundle_from_wire(wire)
        assert b2.seq == 3 and b2.pos == b.pos and b2.limit == b.limit
        # the transport field (round 17) crosses the codec: the dict
        # carries the bundle's value, and a PRE-transport-field
        # artifact (no key) decodes as "wire" — it crossed a socket by
        # definition, so old recorded handoffs still load
        assert wire["transport"] == b.transport
        assert b2.transport == b.transport
        legacy = dict(wire)
        del legacy["transport"]
        assert bundle_from_wire(legacy).transport == "wire"
        np.testing.assert_array_equal(b2.key, np.asarray(b.key))
        for name, arrs in b.pages_payload.items():
            for a, a2 in zip(arrs, b2.pages_payload[name]):
                np.testing.assert_array_equal(np.asarray(a), a2)
        # and the rehydrated bundle still continues byte-exactly
        dst.install_migration(b2)
        while dst.has_work():
            dst.service_round()
        want = _standalone(params, cfg, prompt, 5,
                           key=src.request_key(0), temperature=0.7)
        np.testing.assert_array_equal(dst.finished[0], want)

    def test_draft_engines_refuse_roles_and_migration(self):
        cfg, params = _setup()
        dcfg = TransformerConfig(**{**BASE, "d_model": 16, "d_ff": 32})
        dparams = init_params(jax.random.PRNGKey(1), dcfg)
        eng = EngineCore(params, cfg, **ENG, draft_params=dparams,
                         draft_cfg=dcfg)
        with pytest.raises(ValueError, match="draft"):
            Replica(eng, role="prefill")
        eng.submit(np.arange(5, dtype=np.int32), 4)
        eng.service_round(decode=False)
        with pytest.raises(ValueError, match="draft"):
            eng.export_migration(eng.exportable_slots()[0])

    def test_resume_prefix_submit_path(self):
        # the cross-replica resume the router uses after a replica
        # death: prompt = original + emitted, prefix prepended — the
        # continuation must equal the uninterrupted run (greedy)
        cfg, params = _setup()
        prompt = np.arange(7, dtype=np.int32)
        full = _standalone(params, cfg, prompt, 8)
        cut = 3
        eng = ContinuousBatcher(params, cfg, **ENG)
        eng.submit(np.concatenate([prompt, full[:cut]]), 8 - cut,
                   seq_id=0, resume_prefix=full[:cut])
        got = eng.run()[0]
        np.testing.assert_array_equal(got, full)
        with pytest.raises(ValueError, match="longer"):
            eng.submit(np.arange(2, dtype=np.int32), 3,
                       resume_prefix=np.arange(5, dtype=np.int32))


class TestLadderAutotune:
    def test_fit_beats_default_on_long_tail(self):
        # the round-6 open item's pin: a long-tail mix must fit a
        # ladder with STRICTLY less expected padding than the default
        rng = np.random.RandomState(0)
        lengths = (list(rng.choice([7, 9, 11, 13], size=400))
                   + list(rng.choice([100, 240], size=20)))
        default = bucket_ladder(256)
        fit = fit_bucket_ladder(lengths, max_rungs=len(default),
                                max_len=256)
        assert expected_padding(fit, lengths) \
            < expected_padding(default, lengths)
        assert max(fit) >= 256  # still covers every legal prompt
        assert len(fit) <= len(default)

    def test_fit_is_optimal_on_small_cases(self):
        # brute-force check: the DP must match exhaustive search
        import itertools

        lengths = [2, 2, 5, 9, 9, 9, 14]
        cand = sorted(set(lengths))
        for r in (1, 2, 3):
            fit = fit_bucket_ladder(lengths, r)
            best = min(
                (expected_padding(c + (max(cand),), lengths)
                 for k in range(r)
                 for c in itertools.combinations(cand[:-1], k)),
                default=None)
            assert expected_padding(fit, lengths) == pytest.approx(best)

    def test_fit_guards_and_degenerates(self):
        assert fit_bucket_ladder([5, 5, 5], 3) == (5,)
        assert fit_bucket_ladder([3], 1, max_len=10) == (10,)
        with pytest.raises(ValueError):
            fit_bucket_ladder([], 2)
        with pytest.raises(ValueError):
            fit_bucket_ladder([4], 0)
        # the constructor spelling is attached to bucket_ladder
        assert bucket_ladder.fit is fit_bucket_ladder

    def test_engine_runs_fit_ladder(self):
        # "router and engine use it": an engine built on a fit ladder
        # serves the sample it was fit to, oracle-exact
        cfg, params = _setup()
        reqs = _requests(cfg, 4, seed=13)
        fit = fit_bucket_ladder([len(p) for p, _ in reqs], 3)
        eng = ContinuousBatcher(params, cfg, **ENG,
                                prompt_buckets=fit)
        ids = [eng.submit(p, m) for p, m in reqs]
        got = eng.run()
        for sid, (p, m) in zip(ids, reqs):
            np.testing.assert_array_equal(
                got[sid], _standalone(params, cfg, p, m))
