"""Continuous batching vs static batching: serving throughput.

Usage: python benchmarks/bench_serving.py [--n=N] [--slots=S] [--chunk=K]
         [--mix=0|1] [--buckets=auto|none|16,32,...] [--overlap=0|1]
         [--temp=T] [--topk=K] [--smoke]

The capacity story measured on the REALISTIC stream: N requests with
VARIED prompt lengths (``--mix``, default on) and varied generation
budgets, served (a) statically — batches of ``slots`` rows in arrival
order, rows grouped by prompt length into rectangular sub-batches
(fragmentation), every row paying the longest budget in its batch
(padding) — vs (b) the ContinuousBatcher with the production levers
on: prompt-length BUCKETING (admission prefill compiles bounded by the
ladder size, not the stream's distinct lengths) and OVERLAPPED
admission (prefills enqueue behind the in-flight decode chunk).

Reported per engine run: tokens/s, the admission-bubble fraction
(host admission time exposed with no decode in flight), and the
prefill compile count with the ladder bound it must respect.

Oracle on every run (benchmark-IS-the-test): the engine's per-sequence
tokens must equal standalone paged_generate — same per-request key in
sampled mode — before any number is reported, and the compile count
must not exceed the bucket ladder size.

``--smoke``: the CI shape (seconds on the 8-device CPU mesh) —
tests/test_bench_serving.py runs it in tier-1 and asserts the engine
beats static on the mixed workload.

On-chip protocol note: the engine's host loop pays a tunnel round trip
per chunk; ``--chunk`` amortizes it (the dispatch-amortization
discipline of benchmarks/bench_decode.py). Static batching runs each
sub-batch's whole scan in one dispatch — the comparison is honest
serving reality for both.
"""

import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np

import jax
import jax.numpy as jnp

from hpc_patterns_tpu.models import TransformerConfig
from hpc_patterns_tpu.models.decode import paged_generate
from hpc_patterns_tpu.models.serving import (
    ContinuousBatcher,
    pad_to_bucket,
    prefill_cache_size,
)
from hpc_patterns_tpu.models.transformer import init_params


def arg(name, default, cast=int):
    for a in sys.argv[1:]:
        if a.startswith(f"--{name}="):
            v = a.split("=", 1)[1]
            if cast is bool:  # bool("0") is True; parse it properly
                return v.lower() not in ("0", "false", "no", "")
            return cast(v)
        if a == f"--{name}":
            if cast is not bool:
                raise SystemExit(
                    f"--{name} needs =VALUE (space-separated values "
                    "are not supported by this parser)")
            return True
    return default


def run_bench(*, n, slots, chunk, page_size, prompt_len, max_budget,
              cfg, params, mix=True, buckets="auto", overlap=True,
              temperature=0.0, top_k=0, seed=0, reps=1, quiet=False):
    """One engine-vs-static comparison; returns the metrics dict.
    ``buckets``: 'auto' (ladder over prompt_len), 'none', or a tuple.
    ``reps``: timed repetitions per mode, MIN taken — the shared-host
    CI box is noisy and min-of-reps is the standard load-spike shield.
    Raises AssertionError if the oracle or the compile bound fails."""
    out = print if not quiet else (lambda *a, **k: None)
    if isinstance(buckets, str):
        # 'auto' / 'none' / '8,16,32' — the same resolver the CLI
        # serving surfaces use (harness.cli)
        from hpc_patterns_tpu.harness.cli import parse_buckets

        buckets = parse_buckets(buckets, prompt_len)
    rng = np.random.RandomState(7)
    # the production-shaped stream: prompt lengths spread 1/2..1x, and
    # LONG-TAIL budgets (most requests short, a fifth at the max) —
    # static pays fragmentation (rectangular length groups) AND padding
    # (every row pays its batch's longest budget, usually the max);
    # the engine pays each row's own length and budget
    lengths = ([prompt_len // 2, (3 * prompt_len) // 4, prompt_len]
               if mix else [prompt_len])
    reqs = []
    for _ in range(n):
        t = int(rng.choice(lengths))
        prompt = rng.randint(0, cfg.vocab, size=t).astype(np.int32)
        budget = int(rng.choice(
            [max(1, max_budget // 8), max(1, max_budget // 4),
             max_budget],
            p=[0.5, 0.3, 0.2]))
        reqs.append((prompt, budget))
    total_tokens = sum(b for _, b in reqs)

    pages_per_seq = max(
        ContinuousBatcher.pages_needed(len(p), b, page_size,
                                       padded_len=pad_to_bucket(
                                           buckets, len(p)))
        for p, b in reqs)

    # --- static batching: batches of `slots` in arrival order; rows
    # group by prompt length into rectangular sub-batches, every row
    # pays the batch-max budget
    def run_static():
        outs = {}
        for i in range(0, n, slots):
            batch = reqs[i:i + slots]
            run_len = max(b for _, b in batch)
            bylen = {}
            for j, (p, b) in enumerate(batch):
                bylen.setdefault(len(p), []).append((i + j, p, b))
            for group in bylen.values():
                prompts = jnp.asarray(np.stack([p for _, p, _ in group]))
                toks = np.asarray(paged_generate(
                    params, prompts, cfg, run_len, page_size=page_size))
                for j, (idx, _, b) in enumerate(group):
                    outs[idx] = toks[j, :b]
        return outs

    def make_engine():
        return ContinuousBatcher(
            params, cfg, slots=slots, pool_pages=slots * pages_per_seq,
            pages_per_seq=pages_per_seq, page_size=page_size,
            chunk=chunk, prompt_buckets=buckets, overlap=overlap,
            temperature=temperature, top_k=top_k, seed=seed,
        )

    def run_engine():
        eng = make_engine()
        ids = [eng.submit(p, b) for p, b in reqs]
        got = eng.run()
        return {i: got[sid] for i, sid in enumerate(ids)}, eng

    # warmup (compiles) then timed runs
    compiles_before = prefill_cache_size()  # other engines, this process
    run_static()
    run_engine()
    compiles_warm = prefill_cache_size()
    t_static = t_engine = float("inf")
    bubble = None
    for _ in range(reps):
        t0 = time.perf_counter()
        static_out = run_static()
        t_static = min(t_static, time.perf_counter() - t0)
        t0 = time.perf_counter()
        engine_out, eng = run_engine()
        te = time.perf_counter() - t0
        if te < t_engine:
            # keep the bubble fraction of the rep whose time is
            # reported — mixing min-time with another rep's bubble
            # would pair numbers from different runs
            t_engine, bubble = te, eng.last_bubble_frac
    compiles = prefill_cache_size()

    # oracle before any number is believed: engine rows standalone-exact
    # (same per-request key when sampling), compile count inside the
    # ladder bound, and a WARM engine added no prefill compiles at all
    for i, (prompt, b) in enumerate(reqs):
        want = np.asarray(paged_generate(
            params, jnp.asarray(prompt)[None], cfg, b,
            page_size=page_size,
            key=eng.request_key(i) if temperature > 0 else None,
            temperature=temperature, top_k=top_k))[0]
        np.testing.assert_array_equal(engine_out[i], want,
                                      err_msg=f"engine seq {i}")
        if temperature <= 0:
            np.testing.assert_array_equal(
                static_out[i], want[:len(static_out[i])],
                err_msg=f"static seq {i}")
    assert compiles == compiles_warm, (
        f"warm engine recompiled prefill: {compiles_warm} -> {compiles}")
    distinct = len({len(p) for p, _ in reqs})
    compiles = compiles - compiles_before  # this bench's engine only
    if buckets is not None:
        assert compiles <= len(buckets), (
            f"{compiles} prefill compiles > ladder size {len(buckets)}")

    out(f"serving[{'mixed' if mix else 'uniform'}]: n={n} slots={slots} "
        f"chunk={chunk} prompt<={prompt_len} ({distinct} lengths) "
        f"budgets<={max_budget} tokens={total_tokens} "
        f"buckets={buckets if buckets else 'off'} "
        f"overlap={'on' if overlap else 'off'}")
    out(f"  static  : {t_static:.3f}s  "
        f"{total_tokens / t_static:,.1f} tok/s")
    out(f"  engine  : {t_engine:.3f}s  "
        f"{total_tokens / t_engine:,.1f} tok/s  "
        f"bubble {bubble:.1%}  prefill compiles {compiles}"
        f"{f' (ladder {len(buckets)})' if buckets else ''}")
    out(f"  engine/static speedup: {t_static / t_engine:.3f}x "
        "(oracle-exact)")
    return {
        "t_static": t_static, "t_engine": t_engine,
        "tokens": total_tokens,
        "tokens_per_s_static": total_tokens / t_static,
        "tokens_per_s_engine": total_tokens / t_engine,
        "speedup": t_static / t_engine,
        "bubble_frac": bubble,
        "prefill_compiles": compiles,
        "ladder": len(buckets) if buckets else None,
        "distinct_lengths": distinct,
    }


def smoke_config():
    """The CI shape: a model big enough that DEVICE work (static's
    padding + fragmentation waste vs the engine's own-budget rows)
    dominates host dispatch on the 8-device CPU mesh, with the serving
    gather route so neither side pays pallas interpret cost — ONE
    definition shared by the CLI ``--smoke`` and the tier-1 pytest
    (tests/test_bench_serving.py) so they cannot drift. Engine wins
    ~2.5x here;
    the pytest asserts > 1 with that margin as the noise shield."""
    cfg = TransformerConfig(
        vocab=256, d_model=256, n_heads=4, n_layers=2, d_ff=1024,
        max_seq=256, dtype="float32", decode_attn="gather",
    )
    return dict(n=16, slots=4, chunk=16, page_size=16, prompt_len=32,
                max_budget=192, reps=2, cfg=cfg,
                params=init_params(jax.random.PRNGKey(0), cfg))


def main():
    if arg("smoke", False, bool):
        run_bench(**smoke_config(),
                  overlap=bool(arg("overlap", 1)),
                  buckets=arg("buckets", "auto", str))
        return
    on_tpu = jax.default_backend() == "tpu"
    n = arg("n", 32 if on_tpu else 16)
    slots = arg("slots", 8 if on_tpu else 4)
    chunk = arg("chunk", 16)
    page_size = arg("page", 256 if on_tpu else 16)
    prompt_len = arg("prompt", 512 if on_tpu else 32)
    max_budget = arg("budget", 512 if on_tpu else 192)
    cfg = TransformerConfig(
        vocab=arg("vocab", 32768 if on_tpu else 256),
        d_model=arg("d", 1024 if on_tpu else 256),
        n_heads=arg("heads", 8 if on_tpu else 4),
        n_layers=arg("layers", 8 if on_tpu else 2),
        d_ff=arg("ff", 4096 if on_tpu else 1024),
        max_seq=prompt_len + max_budget,
        dtype="bfloat16" if on_tpu else "float32",
        kv_cache_dtype=arg("cache", "compute", str),
        # off-TPU the serving surfaces take the pure-XLA gather route:
        # a pallas_call runs in interpret mode there, paying per-grid
        # host cost that swamps both sides of the comparison
        decode_attn="flash" if on_tpu else arg("attn", "gather", str),
    )
    params = init_params(jax.random.PRNGKey(0), cfg)
    run_bench(n=n, slots=slots, chunk=chunk, page_size=page_size,
              prompt_len=prompt_len, max_budget=max_budget,
              cfg=cfg, params=params,
              mix=bool(arg("mix", 1)),
              buckets=arg("buckets", "auto", str),
              overlap=bool(arg("overlap", 1)),
              temperature=arg("temp", 0.0, float),
              top_k=arg("topk", 0))


if __name__ == "__main__":
    main()
