"""Parallelism-strategy tests: every sharded result must equal the
single-device oracle (the analytic-validation style of SURVEY.md §4.2),
run as 8-way SPMD on the CPU mesh (conftest.py)."""

import warnings

import numpy as np
import pytest

import jax

from hpc_patterns_tpu.topology import shard_map
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from hpc_patterns_tpu import parallel
from hpc_patterns_tpu.parallel.ring_attention import full_attention

B, T, H, D = 2, 32, 8, 16  # global seq T sharded 8 ways -> 4 per rank


def _qkv(key, dtype=jnp.float32):
    ks = jax.random.split(key, 3)
    shape = (B, T, H, D)
    return tuple(jax.random.normal(k, shape, dtype) for k in ks)


def _shmap_seq(mesh, fn, *arrays, axis="x"):
    """Run a rank-local attention fn over sequence-sharded (dim 1) inputs."""
    spec = P(None, axis, None, None)
    mapped = shard_map(
        fn, mesh=mesh, in_specs=(spec,) * len(arrays), out_specs=spec
    )
    return jax.jit(mapped)(*arrays)


class TestRingAttention:
    @pytest.mark.parametrize("causal", [False, True])
    def test_matches_full_attention(self, mesh8, causal):
        q, k, v = _qkv(jax.random.PRNGKey(0))
        got = _shmap_seq(
            mesh8,
            lambda q, k, v: parallel.ring_attention(q, k, v, "x", causal=causal),
            q, k, v,
        )
        want = full_attention(q, k, v, causal=causal)
        np.testing.assert_allclose(np.asarray(got), np.asarray(want), atol=2e-5)

    def test_bf16_inputs(self, mesh8):
        q, k, v = _qkv(jax.random.PRNGKey(1), jnp.bfloat16)
        got = _shmap_seq(
            mesh8, lambda q, k, v: parallel.ring_attention(q, k, v, "x"), q, k, v
        )
        want = full_attention(q, k, v)
        assert got.dtype == jnp.bfloat16
        np.testing.assert_allclose(
            np.asarray(got, np.float32), np.asarray(want, np.float32), atol=3e-2
        )

    @pytest.mark.parametrize("causal", [False, True])
    def test_flash_impl_matches_full_attention(self, mesh8, causal):
        q, k, v = _qkv(jax.random.PRNGKey(2))
        got = _shmap_seq(
            mesh8,
            lambda q, k, v: parallel.ring_attention(
                q, k, v, "x", causal=causal, impl="flash"
            ),
            q, k, v,
        )
        want = full_attention(q, k, v, causal=causal)
        np.testing.assert_allclose(np.asarray(got), np.asarray(want), atol=2e-5)

    def test_flash_impl_grad_matches_oracle(self, mesh8):
        q, k, v = _qkv(jax.random.PRNGKey(3))
        spec = P(None, "x", None, None)
        ringed = shard_map(
            lambda q, k, v: parallel.ring_attention(
                q, k, v, "x", causal=True, impl="flash"
            ),
            mesh=mesh8, in_specs=(spec,) * 3, out_specs=spec,
        )
        g_got = jax.jit(jax.grad(
            lambda q, k, v: ringed(q, k, v).sum(), argnums=(0, 1, 2)
        ))(q, k, v)
        g_want = jax.grad(
            lambda q, k, v: full_attention(q, k, v, causal=True).sum(),
            argnums=(0, 1, 2),
        )(q, k, v)
        for a, b in zip(g_got, g_want):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=2e-5)

    def test_rejects_bad_impl(self, mesh8):
        with pytest.raises(ValueError, match="impl"):
            shard_map(
                lambda q: parallel.ring_attention(q, q, q, "x", impl="nope"),
                mesh=mesh8,
                in_specs=P(None, "x", None, None),
                out_specs=P(None, "x", None, None),
            )(jnp.zeros((B, T, H, D)))

    def test_rejects_bad_rank(self, mesh8):
        with pytest.raises(ValueError, match="head_dim"):
            shard_map(
                lambda q: parallel.ring_attention(q, q, q, "x"),
                mesh=mesh8, in_specs=P("x"), out_specs=P("x"),
            )(jnp.zeros((8, D)))


class TestUlysses:
    @pytest.mark.parametrize("causal", [False, True])
    def test_matches_full_attention(self, mesh8, causal):
        q, k, v = _qkv(jax.random.PRNGKey(2))
        got = _shmap_seq(
            mesh8,
            lambda q, k, v: parallel.ulysses_attention(q, k, v, "x", causal=causal),
            q, k, v,
        )
        want = full_attention(q, k, v, causal=causal)
        np.testing.assert_allclose(np.asarray(got), np.asarray(want), atol=2e-5)

    @pytest.mark.parametrize("causal", [False, True])
    def test_flash_impl_matches_full_attention(self, mesh8, causal):
        q, k, v = _qkv(jax.random.PRNGKey(4))
        got = _shmap_seq(
            mesh8,
            lambda q, k, v: parallel.ulysses_attention(
                q, k, v, "x", causal=causal, impl="flash"
            ),
            q, k, v,
        )
        want = full_attention(q, k, v, causal=causal)
        np.testing.assert_allclose(np.asarray(got), np.asarray(want), atol=2e-5)

    def test_flash_impl_grad_matches_oracle(self, mesh8):
        # flash's custom VJP composed with the all-to-all backward
        q, k, v = _qkv(jax.random.PRNGKey(5))
        spec = P(None, "x", None, None)
        mapped = shard_map(
            lambda q, k, v: parallel.ulysses_attention(
                q, k, v, "x", causal=True, impl="flash"
            ),
            mesh=mesh8, in_specs=(spec,) * 3, out_specs=spec,
        )
        g_got = jax.jit(jax.grad(
            lambda q, k, v: mapped(q, k, v).sum(), argnums=(0, 1, 2)
        ))(q, k, v)
        g_want = jax.grad(
            lambda q, k, v: full_attention(q, k, v, causal=True).sum(),
            argnums=(0, 1, 2),
        )(q, k, v)
        for a, b in zip(g_got, g_want):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=2e-5)

    def test_heads_must_divide(self, mesh8):
        q = jnp.zeros((B, T, 6, D))  # 6 heads, 8 ranks
        with pytest.raises(Exception, match="divisible|not divisible"):
            _shmap_seq(
                mesh8, lambda q, k, v: parallel.ulysses_attention(q, k, v, "x"),
                q, q, q,
            )


class TestGQANarrowKV:
    """GQA with NARROW K/V (kv_heads < heads) through every impl — each
    must equal the expanded-K/V oracle exactly (no expansion happens
    inside; the oracle builds it explicitly)."""

    def _gqa_qkv(self, key, hkv, h=H, dtype=jnp.float32):
        ks = jax.random.split(key, 3)
        q = jax.random.normal(ks[0], (B, T, h, D), dtype)
        k = jax.random.normal(ks[1], (B, T, hkv, D), dtype)
        v = jax.random.normal(ks[2], (B, T, hkv, D), dtype)
        return q, k, v

    def _want(self, q, k, v, causal=True):
        g = q.shape[2] // k.shape[2]
        return full_attention(
            q, jnp.repeat(k, g, axis=2), jnp.repeat(v, g, axis=2),
            causal=causal,
        )

    @pytest.mark.parametrize("causal", [False, True])
    def test_full_attention_grouped(self, causal):
        q, k, v = self._gqa_qkv(jax.random.PRNGKey(10), hkv=2)
        got = full_attention(q, k, v, causal=causal)
        np.testing.assert_allclose(
            np.asarray(got), np.asarray(self._want(q, k, v, causal)),
            atol=2e-5,
        )

    @pytest.mark.parametrize("impl", ["dense", "flash"])
    def test_ring_narrow_kv(self, mesh8, impl):
        # the narrow K/V block is what circulates: group-factor less
        # ppermute traffic per step
        q, k, v = self._gqa_qkv(jax.random.PRNGKey(11), hkv=2)
        got = _shmap_seq(
            mesh8,
            lambda q, k, v: parallel.ring_attention(
                q, k, v, "x", causal=True, impl=impl
            ),
            q, k, v,
        )
        np.testing.assert_allclose(
            np.asarray(got), np.asarray(self._want(q, k, v)), atol=2e-5
        )

    @pytest.mark.slow  # grad-through-GQA also covered in test_ops
    def test_ring_narrow_kv_grad(self, mesh8):
        q, k, v = self._gqa_qkv(jax.random.PRNGKey(12), hkv=2)
        spec = P(None, "x", None, None)
        ringed = shard_map(
            lambda q, k, v: parallel.ring_attention(
                q, k, v, "x", causal=True, impl="flash"
            ),
            mesh=mesh8, in_specs=(spec,) * 3, out_specs=spec,
        )
        g_got = jax.jit(jax.grad(
            lambda q, k, v: ringed(q, k, v).sum(), argnums=(0, 1, 2)
        ))(q, k, v)
        g_want = jax.grad(
            lambda q, k, v: self._want(q, k, v).sum(), argnums=(0, 1, 2)
        )(q, k, v)
        for a, b in zip(g_got, g_want):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=2e-5)

    @pytest.mark.parametrize("impl", ["dense", "flash"])
    def test_ulysses_narrow_kv_scatter(self, mesh8, impl):
        # kv_heads divides the axis: the narrow K/V ride the all-to-alls
        # — and do so SILENTLY (a warning here would mean the expansion
        # fallback stole the narrow-K/V win from a conforming config)
        q, k, v = self._gqa_qkv(jax.random.PRNGKey(13), hkv=8, h=16)
        with warnings.catch_warnings():
            warnings.filterwarnings("error", message=".*expanding K/V.*")
            got = _shmap_seq(
                mesh8,
                lambda q, k, v: parallel.ulysses_attention(
                    q, k, v, "x", causal=True, impl=impl
                ),
                q, k, v,
            )
        np.testing.assert_allclose(
            np.asarray(got), np.asarray(self._want(q, k, v)), atol=2e-5
        )

    @pytest.mark.slow  # expansion fallback = pre-GQA path, stable
    def test_ulysses_narrow_kv_fallback(self, mesh8):
        # kv_heads does NOT divide the axis: expansion fallback, same
        # math, and LOUD — the lost narrow-K/V exchange saving must not
        # be silent
        q, k, v = self._gqa_qkv(jax.random.PRNGKey(14), hkv=2)
        with pytest.warns(UserWarning, match="expanding K/V"):
            got = _shmap_seq(
                mesh8,
                lambda q, k, v: parallel.ulysses_attention(
                    q, k, v, "x", causal=True
                ),
                q, k, v,
            )
        np.testing.assert_allclose(
            np.asarray(got), np.asarray(self._want(q, k, v)), atol=2e-5
        )


class TestTensorParallel:
    def test_tp_mlp_matches_dense(self, mesh8):
        key = jax.random.PRNGKey(3)
        k1, k2, k3 = jax.random.split(key, 3)
        x = jax.random.normal(k1, (4, 16))
        w1 = jax.random.normal(k2, (16, 64)) / 4
        w2 = jax.random.normal(k3, (64, 16)) / 8
        want = jnp.dot(jax.nn.gelu(jnp.dot(x, w1)), w2)

        for algorithm in ("collective", "ring"):
            got = jax.jit(
                shard_map(
                    lambda x, a, b: parallel.tp_mlp(x, a, b, axis="x",
                                                    algorithm=algorithm),
                    mesh=mesh8,
                    in_specs=(P(), P(None, "x"), P("x", None)),
                    out_specs=P(),
                    # the ppermute ring is replicated by construction but
                    # VMA can't prove it (only psum infers replication)
                    check_vma=(algorithm == "collective"),
                )
            )(x, w1, w2)
            np.testing.assert_allclose(
                np.asarray(got), np.asarray(want), atol=1e-4
            )

    def test_row_parallel_scatter_matches_allreduce_shard(self, mesh8):
        key = jax.random.PRNGKey(4)
        x = jax.random.normal(key, (8, 64))
        w = jax.random.normal(jax.random.PRNGKey(5), (64, 32)) / 8
        want = jnp.dot(x, w)  # then sharded on last dim

        got = jax.jit(
            shard_map(
                lambda xl, wl: parallel.tensor.row_parallel_scatter(
                    xl, wl, axis="x"
                ),
                mesh=mesh8,
                in_specs=(P(None, "x"), P("x", None)),
                out_specs=P(None, "x"),
            )
        )(x, w)
        np.testing.assert_allclose(np.asarray(got), np.asarray(want), atol=1e-4)

    def test_bad_algorithm(self):
        with pytest.raises(ValueError, match="unknown algorithm"):
            parallel.row_parallel(jnp.zeros((2, 2)), jnp.zeros((2, 2)),
                                  axis="x", algorithm="smoke_signals")


class TestPipeline:
    def test_pipeline_equals_sequential_stages(self, mesh8):
        M, F = 6, 16
        key = jax.random.PRNGKey(6)
        x = jax.random.normal(key, (M, 4, F))
        # stage r: affine with stage-specific weights (stacked, sharded on x)
        ws = jax.random.normal(jax.random.PRNGKey(7), (8, F, F)) / 4

        def stage(w, h):
            return jnp.tanh(jnp.dot(h, w))

        got_all = jax.jit(
            shard_map(
                lambda x, w: parallel.pipeline_forward(
                    stage, w[0], x, "x"
                )[None],
                mesh=mesh8,
                in_specs=(P(), P("x", None, None)),
                out_specs=P("x"),
            )
        )(x, ws)
        got = np.asarray(got_all)[-1]  # outputs valid on the last rank

        want = x
        for r in range(8):
            want = stage(ws[r], want)
        np.testing.assert_allclose(got, np.asarray(want), atol=1e-5)


class TestPipeline1F1B:
    def test_schedule_invariants(self):
        for P_, M in ((2, 2), (4, 8), (8, 8), (3, 7)):
            fwd, bwd = parallel.schedule_1f1b(P_, M)
            for r in range(P_):
                # no two ops of one stage share a tick
                ticks = [fwd[(r, m)] for m in range(M)] + \
                        [bwd[(r, m)] for m in range(M)]
                assert len(set(ticks)) == len(ticks), (P_, M, r)
                # activations arrive before their consumer needs them
                if r + 1 < P_:
                    for m in range(M):
                        assert fwd[(r, m)] < fwd[(r + 1, m)], (P_, M, r, m)
                # cotangents walk back one stage per tick
                if r > 0:
                    for m in range(M):
                        assert bwd[(r, m)] < bwd[(r - 1, m)], (P_, M, r, m)
                # 1F1B memory bound: stashed (forwarded, not yet
                # backwarded) microbatches never exceed min(P - r, M)
                events = sorted(
                    [(fwd[(r, m)], 1) for m in range(M)]
                    + [(bwd[(r, m)], -1) for m in range(M)]
                )
                live = peak = 0
                for _, delta in events:
                    live += delta
                    peak = max(peak, live)
                assert peak <= min(P_ - r, M), (P_, M, r, peak)

    def test_grads_match_sequential_oracle(self, mesh8):
        M, B, F = 8, 2, 8
        x = jax.random.normal(jax.random.PRNGKey(8), (M, B, F))
        tgt = jax.random.normal(jax.random.PRNGKey(9), (M, B, F))
        ws = jax.random.normal(jax.random.PRNGKey(10), (8, F, F)) / 3

        def stage(w, h):
            return jnp.tanh(jnp.dot(h, w))

        def loss_fn(y, t):
            return jnp.mean((y - t) ** 2)

        def local(x, t, w):
            loss, grads = parallel.pipeline_train_1f1b(
                stage, w[0], x, t, loss_fn, "x"
            )
            return loss[None], grads[None]

        loss, grads = jax.jit(
            shard_map(
                local,
                mesh=mesh8,
                in_specs=(P(), P(), P("x", None, None)),
                out_specs=(P("x"), P("x", None, None)),
            )
        )(x, tgt, ws)

        # oracle: the same 8-stage net, differentiated end-to-end
        def full_loss(ws):
            total = 0.0
            for m in range(M):
                h = x[m]
                for r in range(8):
                    h = stage(ws[r], h)
                total = total + loss_fn(h, tgt[m])
            return total

        want_g = jax.grad(full_loss)(ws)
        want_loss = full_loss(ws) / M

        # loss valid on the last rank only
        np.testing.assert_allclose(float(np.asarray(loss)[-1]),
                                   float(want_loss), rtol=1e-5)
        got_g = np.asarray(grads).reshape(8, F, F)
        np.testing.assert_allclose(got_g, np.asarray(want_g), atol=1e-4)


class TestPPTPPermute:
    # fast-tier coverage of the pp x tp packed-qkv column permutation
    # (the slow-tier pp x tp oracles in test_pp_model.py exercise it in
    # situ): permute -> contiguous tp split must hand each rank its own
    # [q_r|k_r|v_r] sections, and unpermute must invert exactly
    def test_roundtrip_and_block_layout(self):
        from hpc_patterns_tpu.models import TransformerConfig
        from hpc_patterns_tpu.models.pp import (
            tp_permute_wqkv,
            tp_unpermute_wqkv,
        )

        cfg = TransformerConfig(vocab=32, d_model=8, n_heads=4,
                                n_kv_heads=2, n_layers=2, d_ff=16,
                                max_seq=8, dtype="float32")
        tp = 2
        L, D = cfg.n_layers, cfg.d_model
        S = cfg.kv_heads * cfg.head_dim
        w = jnp.arange(L * D * (D + 2 * S), dtype=jnp.float32).reshape(
            L, D, D + 2 * S)
        perm = tp_permute_wqkv(w, cfg, tp)
        assert perm.shape == w.shape
        np.testing.assert_array_equal(
            np.asarray(tp_unpermute_wqkv(perm, cfg, tp)), np.asarray(w))
        # rank r's contiguous block == [q_r | k_r | v_r]
        q, k, v = np.split(np.asarray(w), [D, D + S], axis=-1)
        Dl, Sl = D // tp, S // tp
        for r, blk in enumerate(np.split(np.asarray(perm), tp, axis=-1)):
            np.testing.assert_array_equal(
                blk,
                np.concatenate(
                    [q[..., r * Dl:(r + 1) * Dl],
                     k[..., r * Sl:(r + 1) * Sl],
                     v[..., r * Sl:(r + 1) * Sl]], axis=-1),
            )
