"""Checkpoint/resume for the training state (params + optimizer + step).

The reference serializes nothing (SURVEY.md §5 "Checkpoint / resume:
None"); this module is the upgrade that makes the flagship training
loop restartable. Orbax handles the TPU specifics: sharded arrays are
saved/restored shard-by-shard (each host writes only what it owns) and
restored arrays land back on their mesh devices with the same
NamedShardings — no full-state host materialization, matching the
sharded-init discipline of models/train.init_train_state.
"""

from __future__ import annotations

from pathlib import Path

import jax
import orbax.checkpoint as ocp


def _checkpointer():
    return ocp.StandardCheckpointer()


def save_checkpoint(path, params, opt_state, step: int = 0) -> str:
    """Write {params, opt_state, step} under ``path`` (a directory).
    Blocks until durable (single-controller semantics)."""
    path = Path(path).resolve()
    ckpt = _checkpointer()
    state = {"params": params, "opt_state": opt_state, "step": step}
    ckpt.save(path / f"step_{step}", state, force=True)
    ckpt.wait_until_finished()
    return str(path / f"step_{step}")


def latest_step(path) -> int | None:
    path = Path(path)
    steps = sorted(
        int(p.name.split("_", 1)[1])
        for p in path.glob("step_*")
        if p.name.split("_", 1)[1].isdigit()
    )
    return steps[-1] if steps else None


def restore_params(path, step: int | None = None):
    """Restore only the params subtree (plus the step), using the
    checkpoint's own metadata for structure — no optimizer template
    needed. The saved opt_state's pytree structure depends on the
    training schedule (constant vs warmup/cosine produce different
    optax states), which an evaluator shouldn't have to reconstruct."""
    path = Path(path).resolve()
    if step is None:
        step = latest_step(path)
        if step is None:
            raise FileNotFoundError(f"no step_* checkpoints under {path}")
    meta = _checkpointer().metadata(path / f"step_{step}")
    # newer orbax wraps the saved tree's metadata in
    # CompositeItemMetadata (.item_metadata.tree); older builds return
    # the metadata tree (a dict) directly
    tree = (meta.item_metadata.tree if hasattr(meta, "item_metadata")
            else meta)
    # request only the params and step subtrees (partial restore): the
    # opt_state (~2x param bytes of Adam moments) is never read off disk
    wanted = {"params": tree["params"], "step": tree["step"]}
    abstract = jax.tree.map(
        lambda m: jax.ShapeDtypeStruct(m.shape, m.dtype)
        if getattr(m, "shape", None) is not None
        else m,
        wanted,
    )
    import dataclasses

    # orbax renamed the partial-restore mechanism: newer builds take
    # partial_restore=True; older ones restore a sub-item iff an empty
    # transforms dict marks the request as transform-style
    fields = {f.name for f in dataclasses.fields(ocp.args.PyTreeRestore)}
    partial = ({"partial_restore": True} if "partial_restore" in fields
               else {"transforms": {}})
    with ocp.Checkpointer(ocp.PyTreeCheckpointHandler()) as ckpt:
        state = ckpt.restore(
            path / f"step_{step}",
            args=ocp.args.PyTreeRestore(
                item=abstract,
                restore_args=ocp.checkpoint_utils.construct_restore_args(abstract),
                **partial,
            ),
        )
    return state["params"], int(state["step"])


def restore_checkpoint(path, params_like, opt_state_like, step: int | None = None):
    """Restore (params, opt_state, step). ``*_like`` provide structure,
    dtypes AND shardings — pass the live (or abstract) state created the
    same way as at save time, and arrays come back sharded onto the mesh
    directly (no host round-trip)."""
    path = Path(path).resolve()
    if step is None:
        step = latest_step(path)
        if step is None:
            raise FileNotFoundError(f"no step_* checkpoints under {path}")
    abstract = jax.tree.map(
        lambda x: jax.ShapeDtypeStruct(x.shape, x.dtype, sharding=getattr(x, "sharding", None))
        if hasattr(x, "shape")
        else x,
        {"params": params_like, "opt_state": opt_state_like, "step": int(step)},
    )
    state = _checkpointer().restore(path / f"step_{step}", abstract)
    return state["params"], state["opt_state"], state["step"]
