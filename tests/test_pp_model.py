"""Pipeline-parallel transformer (models/pp.py): loss and full-parameter
gradients through the 1F1B schedule must equal the single-device
end-to-end autodiff oracle (SURVEY.md §4.2 analytic-validation style)."""

import numpy as np
import pytest

import jax

from hpc_patterns_tpu import topology
from hpc_patterns_tpu.models import TransformerConfig, init_params, loss_fn
from hpc_patterns_tpu.models import pp as pplib

# slow tier: each oracle traces + compiles a full unrolled-1F1B model
# (minutes each on the CPU mesh). Fast-tier PP coverage lives in
# test_parallel.py::TestPipeline1F1B and the per-round dryrun PP leg.
pytestmark = pytest.mark.slow

CFG = dict(vocab=32, d_model=16, n_heads=2, n_layers=4, d_ff=32,
           max_seq=8, dtype="float32")


def _pp_lg(params, tokens, cfg, mesh, **kw):
    """pp_loss_and_grads under ONE jit — the production shape
    (make_pp_train_step jits the whole step). Eagerly driving the
    unrolled-1F1B shard_map dispatches hundreds of tiny multi-device
    programs back to back, which intermittently SIGABRTs the XLA:CPU
    runtime (a dispatch race: observed repeatedly mid-suite on the
    8-device host mesh, never under jit). One eager test stays below
    for the op-by-op path's coverage."""
    return jax.jit(
        lambda p, t: pplib.pp_loss_and_grads(p, t, cfg, mesh, **kw)
    )(params, tokens)


@pytest.fixture(scope="module")
def setup():
    cfg = TransformerConfig(**CFG)
    params = init_params(jax.random.PRNGKey(0), cfg)
    tokens = jax.random.randint(jax.random.PRNGKey(1), (4, 8), 0, 32,
                                "int32")
    want_loss, want_g = jax.value_and_grad(
        lambda p: loss_fn(p, tokens, cfg)
    )(params)
    return cfg, params, tokens, float(want_loss), want_g


class TestPPModel:
    def test_pure_pp_matches_oracle(self, setup):
        cfg, params, tokens, want_loss, want_g = setup
        mesh = topology.make_mesh({"pp": 4}, jax.devices()[:4])
        loss, grads = _pp_lg(
            params, tokens, cfg, mesh, microbatches=2
        )
        np.testing.assert_allclose(float(loss), want_loss, rtol=1e-5)
        for a, b in zip(jax.tree.leaves(grads), jax.tree.leaves(want_g)):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       atol=2e-5)

    def test_dp_x_pp_matches_oracle(self, setup):
        cfg, params, tokens, want_loss, want_g = setup
        mesh = topology.make_mesh({"dp": 2, "pp": 2}, jax.devices()[:4])
        loss, grads = _pp_lg(
            params, tokens, cfg, mesh, microbatches=2, axis_dp="dp"
        )
        np.testing.assert_allclose(float(loss), want_loss, rtol=1e-5)
        for a, b in zip(jax.tree.leaves(grads), jax.tree.leaves(want_g)):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       atol=2e-5)

    def test_train_step_learns(self, setup):
        cfg, params, tokens, _, _ = setup
        mesh = topology.make_mesh({"pp": 2}, jax.devices()[:2])
        p, opt = pplib.init_pp_train_state(jax.random.PRNGKey(0), cfg)
        step = pplib.make_pp_train_step(cfg, mesh, microbatches=2)
        losses = []
        for _ in range(4):
            loss, p, opt = step(p, opt, tokens)
            losses.append(float(loss))
        assert losses[-1] < losses[0], f"no learning: {losses}"

    def test_fsdp_pp_matches_oracle(self, setup):
        # ZeRO-3 stage params: all-gather before the stage scan,
        # reduce-scatter grads — loss AND the (gathered) gradients must
        # equal the single-device autodiff oracle
        cfg, params, tokens, want_loss, want_g = setup
        mesh = topology.make_mesh({"fsdp": 2, "pp": 2}, jax.devices()[:4])
        loss, grads = _pp_lg(
            params, tokens, cfg, mesh, microbatches=2, axis_fsdp="fsdp"
        )
        np.testing.assert_allclose(float(loss), want_loss, rtol=1e-5)
        for a, b in zip(jax.tree.leaves(grads), jax.tree.leaves(want_g)):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       atol=2e-5)

    def test_dp_x_fsdp_x_pp_matches_oracle(self, setup):
        # the full composition on 8 devices: batch over dp x fsdp,
        # stage params ZeRO-sharded over fsdp, stages over pp
        cfg, params, tokens, want_loss, want_g = setup
        mesh = topology.make_mesh({"dp": 2, "fsdp": 2, "pp": 2},
                                  jax.devices()[:8])
        loss, grads = _pp_lg(
            params, tokens, cfg, mesh, microbatches=1, axis_dp="dp",
            axis_fsdp="fsdp"
        )
        np.testing.assert_allclose(float(loss), want_loss, rtol=1e-5)
        for a, b in zip(jax.tree.leaves(grads), jax.tree.leaves(want_g)):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       atol=2e-5)

    def test_fsdp_pp_train_state_sharded_and_learns(self, setup):
        # init places layer leaves sharded over (pp, fsdp); the step
        # consumes/produces that placement (grads match params) and the
        # loss goes down
        cfg, params, tokens, _, _ = setup
        mesh = topology.make_mesh({"fsdp": 2, "pp": 2}, jax.devices()[:4])
        p, opt = pplib.init_pp_train_state(
            jax.random.PRNGKey(0), cfg, mesh=mesh, axis_fsdp="fsdp"
        )
        spec = p["layers"]["wqkv"].sharding.spec
        assert "fsdp" in str(spec) and "pp" in str(spec), spec
        step = pplib.make_pp_train_step(cfg, mesh, microbatches=2,
                                        axis_fsdp="fsdp")
        losses = []
        for _ in range(4):
            loss, p, opt = step(p, opt, tokens)
            losses.append(float(loss))
        assert losses[-1] < losses[0], f"no learning: {losses}"
        spec = p["layers"]["wqkv"].sharding.spec
        assert "fsdp" in str(spec), (
            f"params lost fsdp sharding through the update: {spec}"
        )

    def test_gqa_pp_matches_oracle(self):
        # GQA (narrow K/V heads) must compose with the pipeline like it
        # does with every other strategy: the stage body is the same
        # _layer the flagship model runs, so narrow-K/V stages must
        # reproduce the end-to-end oracle exactly
        cfg = TransformerConfig(**{**CFG, "n_heads": 4, "n_kv_heads": 2})
        params = init_params(jax.random.PRNGKey(0), cfg)
        tokens = jax.random.randint(jax.random.PRNGKey(1), (4, 8), 0, 32,
                                    "int32")
        want_loss, want_g = jax.value_and_grad(
            lambda p: loss_fn(p, tokens, cfg)
        )(params)
        mesh = topology.make_mesh({"pp": 2}, jax.devices()[:2])
        loss, grads = _pp_lg(
            params, tokens, cfg, mesh, microbatches=2
        )
        np.testing.assert_allclose(float(loss), float(want_loss),
                                   rtol=1e-5)
        for a, b in zip(jax.tree.leaves(grads), jax.tree.leaves(want_g)):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       atol=2e-5)

    def test_tp_x_pp_matches_oracle(self, setup):
        # Megatron tp INSIDE pipeline stages (the canonical large-model
        # layout): column/row-split stage weights, f/g custom-vjp
        # boundaries, permuted packed-qkv — loss AND full grads (in the
        # standard public layout) must equal single-device autodiff
        cfg, params, tokens, want_loss, want_g = setup
        mesh = topology.make_mesh({"pp": 2, "tp": 2}, jax.devices()[:4])
        loss, grads = _pp_lg(
            params, tokens, cfg, mesh, microbatches=2, axis_tp="tp"
        )
        np.testing.assert_allclose(float(loss), want_loss, rtol=1e-5)
        for (ka, a), (kb, b) in zip(
            jax.tree_util.tree_leaves_with_path(grads),
            jax.tree_util.tree_leaves_with_path(want_g),
        ):
            np.testing.assert_allclose(
                np.asarray(a), np.asarray(b), atol=2e-5,
                err_msg=f"{jax.tree_util.keystr(ka)}",
            )

    def test_dp_x_tp_x_pp_matches_oracle(self, setup):
        # the 3-axis composition: batch over dp, stages over pp, tp
        # splitting each stage's weights — 8-device mesh
        cfg, params, tokens, want_loss, want_g = setup
        mesh = topology.make_mesh({"dp": 2, "pp": 2, "tp": 2},
                                  jax.devices()[:8])
        loss, grads = _pp_lg(
            params, tokens, cfg, mesh, microbatches=2, axis_dp="dp",
            axis_tp="tp",
        )
        np.testing.assert_allclose(float(loss), want_loss, rtol=1e-5)
        for a, b in zip(jax.tree.leaves(grads), jax.tree.leaves(want_g)):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       atol=2e-5)

    def test_gqa_tp_x_pp_matches_oracle(self):
        # narrow-K/V stage attention under tp: local shards keep whole
        # kv heads (tp=2 over n_kv_heads=2), group factor preserved
        cfg = TransformerConfig(**{**CFG, "n_heads": 4, "n_kv_heads": 2})
        params = init_params(jax.random.PRNGKey(0), cfg)
        tokens = jax.random.randint(jax.random.PRNGKey(1), (4, 8), 0, 32,
                                    "int32")
        want_loss, want_g = jax.value_and_grad(
            lambda p: loss_fn(p, tokens, cfg)
        )(params)
        mesh = topology.make_mesh({"pp": 2, "tp": 2}, jax.devices()[:4])
        loss, grads = _pp_lg(
            params, tokens, cfg, mesh, microbatches=2, axis_tp="tp"
        )
        np.testing.assert_allclose(float(loss), float(want_loss),
                                   rtol=1e-5)
        for a, b in zip(jax.tree.leaves(grads), jax.tree.leaves(want_g)):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       atol=2e-5)

    def test_fsdp_x_tp_x_pp_matches_oracle(self, setup):
        # ZeRO-3 param storage + Megatron stage compute + pipeline:
        # the fsdp all-gather targets the dim tp leaves unsharded, so
        # the two weight shardings compose inside one shard_map
        cfg, params, tokens, want_loss, want_g = setup
        mesh = topology.make_mesh({"fsdp": 2, "pp": 2, "tp": 2},
                                  jax.devices()[:8])
        loss, grads = _pp_lg(
            params, tokens, cfg, mesh, microbatches=2, axis_fsdp="fsdp",
            axis_tp="tp",
        )
        np.testing.assert_allclose(float(loss), want_loss, rtol=1e-5)
        for a, b in zip(jax.tree.leaves(grads), jax.tree.leaves(want_g)):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       atol=2e-5)

    @pytest.mark.parametrize("over", [
        {"loss_chunk": 16},  # chunked loss keeps the replicated head
        {"vocab": 33},       # vocab % tp != 0: replicated fallback
    ])
    def test_tp_pp_head_fallback_matches_oracle(self, over):
        # configs the Megatron (vocab-sharded) head cannot serve fall
        # back to the replicated head instead of rejecting — and still
        # match single-device autodiff exactly
        cfg = TransformerConfig(**{**CFG, **over})
        params = init_params(jax.random.PRNGKey(0), cfg)
        tokens = jax.random.randint(jax.random.PRNGKey(1), (4, 8), 0,
                                    cfg.vocab, "int32")
        want_loss, want_g = jax.value_and_grad(
            lambda p: loss_fn(p, tokens, cfg)
        )(params)
        mesh = topology.make_mesh({"pp": 2, "tp": 2}, jax.devices()[:4])
        loss, grads = _pp_lg(params, tokens, cfg, mesh, microbatches=2,
                             axis_tp="tp")
        np.testing.assert_allclose(float(loss), float(want_loss),
                                   rtol=1e-5)
        for a, b in zip(jax.tree.leaves(grads), jax.tree.leaves(want_g)):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       atol=2e-5)

    def test_tp_pp_rejects_moe_and_indivisible(self):
        cfg = TransformerConfig(**{**CFG, "n_experts": 2})
        params = init_params(jax.random.PRNGKey(0), cfg)
        tokens = jax.random.randint(jax.random.PRNGKey(1), (4, 8), 0, 32,
                                    "int32")
        mesh = topology.make_mesh({"pp": 2, "tp": 2}, jax.devices()[:4])
        with pytest.raises(ValueError, match="MoE"):
            _pp_lg(params, tokens, cfg, mesh,
                                    microbatches=2, axis_tp="tp")
        bad = TransformerConfig(**{**CFG, "n_heads": 1})
        paramsb = init_params(jax.random.PRNGKey(0), bad)
        with pytest.raises(ValueError, match="divide"):
            _pp_lg(paramsb, tokens, bad, mesh,
                                    microbatches=2, axis_tp="tp")

    def test_fused_mlp_pp_matches_oracle(self):
        # the Pallas fused MLP inside pipeline stages (mesh=None stage
        # math, interpret mode on CPU) must reproduce the dense oracle
        cfg = TransformerConfig(**{**CFG, "mlp_impl": "fused"})
        dense = TransformerConfig(**CFG)
        params = init_params(jax.random.PRNGKey(0), dense)
        tokens = jax.random.randint(jax.random.PRNGKey(1), (4, 8), 0, 32,
                                    "int32")
        want_loss, want_g = jax.value_and_grad(
            lambda p: loss_fn(p, tokens, dense)
        )(params)
        mesh = topology.make_mesh({"pp": 2}, jax.devices()[:2])
        loss, grads = _pp_lg(
            params, tokens, cfg, mesh, microbatches=2
        )
        np.testing.assert_allclose(float(loss), float(want_loss),
                                   rtol=1e-5)
        for a, b in zip(jax.tree.leaves(grads), jax.tree.leaves(want_g)):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       atol=5e-4)

    def test_rope_pp_matches_oracle(self):
        # rope params have no pos_embed entry; the pp grads dict must
        # mirror that and still match the end-to-end oracle
        cfg = TransformerConfig(**{**CFG, "pos_embed": "rope"})
        params = init_params(jax.random.PRNGKey(0), cfg)
        tokens = jax.random.randint(jax.random.PRNGKey(1), (4, 8), 0, 32,
                                    "int32")
        want_loss, want_g = jax.value_and_grad(
            lambda p: loss_fn(p, tokens, cfg)
        )(params)
        mesh = topology.make_mesh({"pp": 2}, jax.devices()[:2])
        loss, grads = _pp_lg(
            params, tokens, cfg, mesh, microbatches=2
        )
        assert "pos_embed" not in grads
        np.testing.assert_allclose(float(loss), float(want_loss), rtol=1e-5)
        for a, b in zip(jax.tree.leaves(grads), jax.tree.leaves(want_g)):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       atol=2e-5)

    @pytest.mark.parametrize("dp", [None, "dp"])
    def test_pp_moe_matches_oracle(self, dp):
        # PP x MoE: the load-balance aux loss threads through the 1F1B
        # schedule (stage_aux_weight). Oracle semantics are per-
        # microbatch: routing fractions and capacity are computed per
        # microbatch in the pipeline, so the reference loss is the mean
        # of loss_fn over the same microbatch slices (aux is nonlinear
        # in the batch, so the full-batch loss_fn would NOT match).
        cfg = TransformerConfig(**{**CFG, "n_layers": 2, "n_experts": 2,
                                   "capacity_factor": 2.0})
        params = init_params(jax.random.PRNGKey(2), cfg)
        tokens = jax.random.randint(jax.random.PRNGKey(3), (8, 8), 0, 32,
                                    "int32")
        M, dsize = 2, (2 if dp else 1)

        def oracle(p):
            mbs = tokens.reshape(M * dsize, -1, tokens.shape[-1])
            return sum(loss_fn(p, mb, cfg) for mb in mbs) / (M * dsize)

        want_loss, want_g = jax.value_and_grad(oracle)(params)
        axes = {"dp": 2, "pp": 2} if dp else {"pp": 2}
        mesh = topology.make_mesh(axes, jax.devices()[:2 * dsize])
        loss, grads = _pp_lg(
            params, tokens, cfg, mesh, microbatches=M, axis_dp=dp
        )
        np.testing.assert_allclose(float(loss), float(want_loss), rtol=1e-5)
        for a, b in zip(jax.tree.leaves(grads), jax.tree.leaves(want_g)):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       atol=2e-5)

    def test_pp_chunked_loss_matches_oracle(self, setup):
        # --pp x --loss-chunk: the last stage's loss head computes the
        # per-microbatch NLL by online logsumexp over vocab chunks (the
        # logits never materialize) and must equal the dense-head
        # single-device oracle — loss AND grads (the chunked head's
        # backward recomputes each chunk inside the 1F1B tick)
        cfg, params, tokens, want_loss, want_g = setup
        ccfg = TransformerConfig(**{**CFG, "loss_chunk": 8})
        mesh = topology.make_mesh({"pp": 4}, jax.devices()[:4])
        loss, grads = _pp_lg(
            params, tokens, ccfg, mesh, microbatches=2
        )
        np.testing.assert_allclose(float(loss), want_loss, rtol=1e-5)
        for a, b in zip(jax.tree.leaves(grads), jax.tree.leaves(want_g)):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       atol=2e-5)

    def test_layers_must_divide(self, setup):
        cfg, params, tokens, _, _ = setup
        mesh = topology.make_mesh({"pp": 4}, jax.devices()[:4])
        bad = TransformerConfig(**{**CFG, "n_layers": 6})
        with pytest.raises(ValueError, match="divide"):
            _pp_lg(
                init_params(jax.random.PRNGKey(0), bad), tokens, bad, mesh,
                microbatches=4,
            )
