"""The jaxlint rule set: the hazard classes this repo has hit or is
one typo away from.

Each rule is a pure-``ast`` visitor over one module (cross-module
resolution is deliberately out of scope: every hazard below is visible
— and was introduced — within a single file). Canonical-name matching
goes through :meth:`ModuleInfo.resolve`, so ``np``/``numpy`` and
``jnp``/``jax.numpy`` spellings are equivalent.

Catalog (docs/analysis.md has the worked examples):

- ``donation-alias``       — zero-copy host view live across a call
                             that donates the viewed buffer (the PR 2
                             ``_dispatch_chunk`` bug, verbatim)
- ``host-sync-in-dispatch``— host readback/sync inside a
                             dispatch-critical function
- ``recompile-hazard``     — ``jax.jit`` built per call / per loop
                             iteration; fresh containers as static args
- ``prng-key-reuse``       — one key consumed by two traced uses with
                             no ``split``/``fold_in`` between
- ``tracer-leak``          — traced intermediates assigned to
                             ``self.*``/globals inside a jitted body

The **shardlint family** (PR 6) guards the SPMD divergence hazard
class — the reference suite's silent MPI deadlock, where ranks
disagree on which collective comes next (its runtime complement is
the collective schedule verifier in ``analysis/runtime.py``):

- ``collective-divergence``— a collective issued under rank-dependent
                             control flow whose paths disagree on the
                             collective sequence (branch arms, early
                             returns, rank-sized loops)
- ``collective-order``     — two sibling code paths issue the SAME
                             collectives in DIFFERENT orders
- ``unchecked-permutation``— a ppermute pair list that never flowed
                             through ``comm.ring.check_permutation``
- ``spec-mismatch``        — PartitionSpec literals inconsistent with
                             the module's declared mesh axes (unknown
                             or duplicated axis names), or a donated
                             arg's in-sharding matching no out-sharding
"""

from __future__ import annotations

import ast
from typing import Iterable

from hpc_patterns_tpu.analysis.core import (
    AnalysisConfig,
    Finding,
    ModuleInfo,
    Rule,
    register,
)

# calls returning a zero-copy host view of their argument (on CPU, and
# for np.asarray/__array__ whenever XLA can hand back the host buffer)
_VIEW_CALLS = frozenset({"numpy.asarray", "memoryview"})
# jax.random calls that CONSUME the key passed as their first argument.
# fold_in is exempt: folding distinct data into one base key is the
# documented fan-out pattern (serving.request_key); PRNGKey/key CREATE.
_KEY_EXEMPT = frozenset({
    "fold_in", "PRNGKey", "key", "clone", "key_data", "wrap_key_data",
    "key_impl", "default_prng_impl",
})
_JIT_NAMES = frozenset({
    "jax.jit", "jax.pjit", "jax.experimental.pjit.pjit",
})


def _func_name(mod: ModuleInfo, call: ast.Call) -> str | None:
    return mod.resolve(call.func)


def _is_jit_constructor(mod: ModuleInfo, call: ast.Call) -> bool:
    """``jax.jit(...)`` or ``partial(jax.jit, ...)`` (pjit included)."""
    name = _func_name(mod, call)
    if name in _JIT_NAMES:
        return True
    if name == "functools.partial" and call.args:
        return mod.resolve(call.args[0]) in _JIT_NAMES
    return False


def _int_tuple(node: ast.AST) -> tuple[int, ...] | None:
    """Literal int / tuple-or-list-of-ints, else None."""
    if isinstance(node, ast.Constant) and isinstance(node.value, int):
        return (node.value,)
    if isinstance(node, (ast.Tuple, ast.List)):
        out = []
        for elt in node.elts:
            if not (isinstance(elt, ast.Constant)
                    and isinstance(elt.value, int)):
                return None
            out.append(elt.value)
        return tuple(out)
    return None


def _str_tuple(node: ast.AST) -> tuple[str, ...]:
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return (node.value,)
    if isinstance(node, (ast.Tuple, ast.List)):
        return tuple(
            elt.value for elt in node.elts
            if isinstance(elt, ast.Constant)
            and isinstance(elt.value, str)
        )
    return ()


def _jit_call_config(mod: ModuleInfo, call: ast.Call
                     ) -> dict[str, tuple]:
    """donate_argnums/donate_argnames/static_argnames literals from a
    jit constructor call (works for the ``partial(jax.jit, ...)`` form
    too — keywords live on the partial)."""
    out: dict[str, tuple] = {}
    for kw in call.keywords:
        if kw.arg == "donate_argnums":
            nums = _int_tuple(kw.value)
            if nums is not None:
                out["donate_argnums"] = nums
        elif kw.arg == "donate_argnames":
            out["donate_argnames"] = _str_tuple(kw.value)
        elif kw.arg == "static_argnames":
            out["static_argnames"] = _str_tuple(kw.value)
    return out


def _donor_table(mod: ModuleInfo) -> dict[str, dict[str, tuple]]:
    """name -> jit config for every donating callable visible in this
    module: decorated defs and ``name = jax.jit(f, donate_...)``."""
    donors: dict[str, dict[str, tuple]] = {}
    for node in ast.walk(mod.tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            for dec in node.decorator_list:
                if isinstance(dec, ast.Call) and _is_jit_constructor(
                        mod, dec):
                    cfg = _jit_call_config(mod, dec)
                    if "donate_argnums" in cfg or "donate_argnames" in cfg:
                        donors[node.name] = cfg
        elif isinstance(node, ast.Assign) and isinstance(
                node.value, ast.Call) and _is_jit_constructor(
                    mod, node.value):
            cfg = _jit_call_config(mod, node.value)
            if "donate_argnums" in cfg or "donate_argnames" in cfg:
                for tgt in node.targets:
                    if isinstance(tgt, ast.Name):
                        donors[tgt.id] = cfg
    return donors


def _functions(tree: ast.AST) -> Iterable[ast.FunctionDef]:
    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            yield node


def _loop_ancestors(mod: ModuleInfo, node: ast.AST) -> set[int]:
    """ids of the For/While nodes enclosing ``node``."""
    out: set[int] = set()
    cur = mod.parents.get(node)
    while cur is not None:
        if isinstance(cur, (ast.For, ast.While)):
            out.add(id(cur))
        cur = mod.parents.get(cur)
    return out


@register
class DonationAliasRule(Rule):
    """The PR 2 bug class: ``v = np.asarray(x)`` is (on CPU, and
    whenever XLA can avoid the copy) a zero-copy HOST VIEW of ``x``'s
    device buffer. If ``x`` is then passed to a call that DONATES it,
    any executable honoring the donation (cache-loaded ones do, round
    6) reuses the buffer for the output — and the "snapshot" silently
    mutates under the host's feet."""

    name = "donation-alias"
    summary = ("zero-copy host view of a buffer that a later call "
               "donates")
    hint = ("snapshot with np.array(x) (a real copy) before the "
            "donating call, or defer the host read past it")

    def check(self, mod: ModuleInfo, config: AnalysisConfig
              ) -> Iterable[Finding]:
        donors = _donor_table(mod)
        if not donors:
            return
        for fn in _functions(mod.tree):
            # views: var -> (source-expr dump, assign line)
            views: dict[str, tuple[str, int, ast.AST]] = {}
            donating: list[tuple[int, str, ast.Call]] = []
            loads: dict[str, list[int]] = {}
            returns: list[tuple[int, ast.Return]] = []
            for node in ast.walk(fn):
                if (isinstance(node, ast.Assign)
                        and len(node.targets) == 1
                        and isinstance(node.targets[0], ast.Name)
                        and isinstance(node.value, ast.Call)):
                    call = node.value
                    cname = _func_name(mod, call)
                    is_view = cname in _VIEW_CALLS
                    if (cname == "numpy.array" and any(
                            kw.arg == "copy"
                            and isinstance(kw.value, ast.Constant)
                            and kw.value.value is False
                            for kw in call.keywords)):
                        is_view = True  # np.array(x, copy=False)
                    src: ast.AST | None = None
                    if (is_view and call.args and isinstance(
                            call.args[0], (ast.Name, ast.Attribute,
                                           ast.Subscript))):
                        src = call.args[0]
                    elif (isinstance(call.func, ast.Attribute)
                            and call.func.attr == "__array__"
                            and isinstance(
                                call.func.value,
                                (ast.Name, ast.Attribute,
                                 ast.Subscript))):
                        src = call.func.value  # x.__array__()
                    if src is not None:
                        views[node.targets[0].id] = (
                            ast.dump(src), node.lineno, node)
                elif isinstance(node, ast.Call):
                    cname = _func_name(mod, node)
                    donor = donors.get((cname or "").split(".")[-1]) \
                        if cname else None
                    if donor is not None:
                        for i in donor.get("donate_argnums", ()):
                            if i < len(node.args):
                                donating.append(
                                    (node.lineno,
                                     ast.dump(node.args[i]), node))
                        names = donor.get("donate_argnames", ())
                        for kw in node.keywords:
                            if kw.arg in names:
                                donating.append(
                                    (node.lineno, ast.dump(kw.value),
                                     node))
                elif isinstance(node, ast.Name) and isinstance(
                        node.ctx, ast.Load):
                    loads.setdefault(node.id, []).append(node.lineno)
                elif isinstance(node, ast.Return):
                    returns.append((node.lineno, node))
            for var, (src_dump, vline, vnode) in views.items():
                for dline, arg_dump, call in donating:
                    if arg_dump != src_dump:
                        continue
                    if dline > vline:
                        # textual order: view taken, THEN donated
                        used_after = any(
                            ln > dline for ln in loads.get(var, ()))
                    elif _loop_ancestors(mod, vnode) & _loop_ancestors(
                            mod, call):
                        # shared loop: iteration N's view is still live
                        # when iteration N+1's donation (textually
                        # earlier) clobbers the buffer
                        used_after = any(
                            ln > vline for ln in loads.get(var, ()))
                    else:
                        continue
                    if used_after:
                        yield self.finding(
                            mod, vnode,
                            f"{var!r} is a zero-copy host view of a "
                            f"buffer donated by the call at line "
                            f"{dline}; an executable honoring the "
                            f"donation mutates the view in place",
                        )
                        break


@register
class HostSyncRule(Rule):
    """Dispatch-critical functions (the overlapped serving path, eager
    collective bodies — ``AnalysisConfig.dispatch_critical``, or any
    function decorated ``@dispatch_critical``) exist to keep the device
    queue fed. A host readback (``np.asarray``/``np.array`` of a device
    value, ``.item()``, ``float()`` of a device result,
    ``block_until_ready``, ``device_get``) stalls exactly the pipeline
    they implement."""

    name = "host-sync-in-dispatch"
    summary = "host readback/sync inside a dispatch-critical function"
    hint = ("defer the readback to the loop's sync point (the "
            "serving pattern: _resolve_pending / _collect_chunk), or "
            "keep the decision on device")

    _SYNC_CALLS = frozenset({
        "jax.block_until_ready", "jax.device_get",
        "numpy.asarray", "numpy.array",
    })
    _SYNC_METHODS = frozenset({"item", "block_until_ready"})
    _SYNC_CASTS = frozenset({"float", "int", "bool"})

    def _is_critical(self, fn: ast.FunctionDef,
                     config: AnalysisConfig) -> bool:
        if fn.name in config.dispatch_critical:
            return True
        for dec in fn.decorator_list:
            node = dec.func if isinstance(dec, ast.Call) else dec
            name = node.attr if isinstance(node, ast.Attribute) else (
                node.id if isinstance(node, ast.Name) else "")
            if name == "dispatch_critical":
                return True
        return False

    def check(self, mod: ModuleInfo, config: AnalysisConfig
              ) -> Iterable[Finding]:
        for fn in _functions(mod.tree):
            if not self._is_critical(fn, config):
                continue
            for node in ast.walk(fn):
                if not isinstance(node, ast.Call):
                    continue
                cname = _func_name(mod, node)
                if cname in self._SYNC_CALLS:
                    yield self.finding(
                        mod, node,
                        f"{cname}() forces a host sync inside "
                        f"dispatch-critical {fn.name!r}",
                    )
                elif (isinstance(node.func, ast.Attribute)
                        and node.func.attr in self._SYNC_METHODS):
                    yield self.finding(
                        mod, node,
                        f".{node.func.attr}() forces a host sync "
                        f"inside dispatch-critical {fn.name!r}",
                    )
                elif (cname in self._SYNC_CASTS and node.args
                        and isinstance(node.args[0], ast.Call)):
                    # float(f(...)): materializes the device result —
                    # the cast-of-a-call form only, so host-side
                    # int(x.size) bookkeeping stays legal
                    yield self.finding(
                        mod, node,
                        f"{cname}() of a call result reads back a "
                        f"device value inside dispatch-critical "
                        f"{fn.name!r}",
                    )


@register
class RecompileRule(Rule):
    """``jax.jit`` keys its trace cache on the wrapper object: a
    wrapper constructed per call (or per loop iteration) re-traces and
    re-compiles every time — the silent 1000x slowdown. Static args
    add the variant: a fresh unhashable container as a static arg
    fails (or, for exotic __eq__ types, recompiles) on every call."""

    name = "recompile-hazard"
    summary = ("jit constructed per call/iteration, or fresh "
               "containers as static args")
    hint = ("hoist the jit to module level (or memoize the wrapper); "
            "pass static args as hashable constants")

    def check(self, mod: ModuleInfo, config: AnalysisConfig
              ) -> Iterable[Finding]:
        # static-arg tables for same-module jitted defs
        statics: dict[str, frozenset[str]] = {}
        for fn in _functions(mod.tree):
            for dec in fn.decorator_list:
                if isinstance(dec, ast.Call) and _is_jit_constructor(
                        mod, dec):
                    names = _jit_call_config(mod, dec).get(
                        "static_argnames", ())
                    if names:
                        statics[fn.name] = frozenset(names)
        for node in ast.walk(mod.tree):
            if not isinstance(node, ast.Call):
                continue
            if _is_jit_constructor(mod, node):
                loop = self._enclosing(mod, node, (ast.For, ast.While))
                fn = self._enclosing(
                    mod, node, (ast.FunctionDef, ast.AsyncFunctionDef))
                parent = mod.parents.get(node)
                called_now = (isinstance(parent, ast.Call)
                              and parent.func is node)
                if loop is not None:
                    yield self.finding(
                        mod, node,
                        "jax.jit constructed inside a loop: a fresh "
                        "wrapper per iteration re-traces and "
                        "re-compiles every time",
                    )
                elif fn is not None and called_now:
                    yield self.finding(
                        mod, node,
                        f"jax.jit(...)(...) inside {fn.name!r}: the "
                        f"wrapper is rebuilt — and re-jitted — on "
                        f"every call of {fn.name!r}",
                    )
            else:
                cname = _func_name(mod, node)
                static = statics.get((cname or "").split(".")[-1]) \
                    if cname else None
                if not static:
                    continue
                for kw in node.keywords:
                    if kw.arg in static and isinstance(
                            kw.value, (ast.List, ast.Dict, ast.Set)):
                        yield self.finding(
                            mod, kw.value,
                            f"fresh {type(kw.value).__name__.lower()} "
                            f"literal passed as static arg "
                            f"{kw.arg!r} of jitted "
                            f"{(cname or '').split('.')[-1]!r}",
                            hint="static args are hashed into the "
                                 "compile cache key; pass a tuple / "
                                 "frozen constant",
                        )

    @staticmethod
    def _enclosing(mod: ModuleInfo, node: ast.AST, kinds) -> ast.AST | None:
        cur = mod.parents.get(node)
        while cur is not None:
            if isinstance(cur, kinds):
                return cur
            cur = mod.parents.get(cur)
        return None


@register
class PrngReuseRule(Rule):
    """A PRNG key is an affine resource: every ``jax.random`` consumer
    (including ``split``) must see a key exactly once, or two "random"
    draws are bit-identical. ``fold_in`` is the sanctioned fan-out
    (distinct data into one base — serving.request_key) and is exempt."""

    name = "prng-key-reuse"
    summary = "one key consumed by two traced uses without a re-split"
    hint = ("thread the key: `key, sub = jax.random.split(key)` before "
            "each consumer, or fold_in distinct stream ids")

    def check(self, mod: ModuleInfo, config: AnalysisConfig
              ) -> Iterable[Finding]:
        findings: list[Finding] = []
        for fn in _functions(mod.tree):
            state: dict[str, int] = {}  # var -> first-consumption line
            self._scan_block(mod, fn.body, state, findings, fn)
        seen = set()
        for f in findings:
            if (f.line, f.col) not in seen:
                seen.add((f.line, f.col))
                yield f

    # -- helpers ---------------------------------------------------------

    def _consumptions(self, mod: ModuleInfo, expr: ast.AST
                      ) -> list[tuple[str, ast.Call]]:
        out = []
        stack = [expr]
        while stack:
            node = stack.pop()
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                                 ast.ClassDef)):
                continue  # separate scope, scanned on its own
            stack.extend(ast.iter_child_nodes(node))
            if not (isinstance(node, ast.Call) and node.args
                    and isinstance(node.args[0], ast.Name)):
                continue
            cname = _func_name(mod, node) or ""
            if (cname.startswith("jax.random.")
                    and cname.rsplit(".", 1)[1] not in _KEY_EXEMPT):
                out.append((node.args[0].id, node))
        return out

    def _targets(self, node: ast.AST) -> set[str]:
        names: set[str] = set()
        for t in ast.walk(node):
            if isinstance(t, ast.Name) and isinstance(
                    t.ctx, (ast.Store, ast.Del)):
                names.add(t.id)
        return names

    def _scan_block(self, mod, stmts, state, findings, fn):
        for stmt in stmts:
            if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef,
                                 ast.ClassDef)):
                continue  # separate scope, scanned on its own
            if isinstance(stmt, (ast.For, ast.While)):
                # a key consumed in a loop body that never re-splits it
                # draws the SAME bits every iteration, whether the key
                # is a param, an outer local, or pre-loop state
                assigned = self._targets(stmt)
                body = stmt.body + stmt.orelse
                for sub in body:
                    for var, call in self._consumptions(mod, sub):
                        if var not in assigned:
                            findings.append(self.finding(
                                mod, call,
                                f"key {var!r} consumed inside a loop "
                                f"without a re-split in the loop body "
                                f"(every iteration sees the same "
                                f"key)",
                            ))
                self._scan_block(mod, body, state, findings, fn)
                continue
            if isinstance(stmt, ast.If):
                self._consume_expr(mod, stmt.test, state, findings)
                s1, s2 = dict(state), dict(state)
                self._scan_block(mod, stmt.body, s1, findings, fn)
                self._scan_block(mod, stmt.orelse, s2, findings, fn)
                # conservative merge: consumed in either branch counts
                state.clear()
                for d in (s1, s2):
                    for k, v in d.items():
                        state.setdefault(k, v)
                continue
            if isinstance(stmt, (ast.With, ast.AsyncWith)):
                for item in stmt.items:
                    self._consume_expr(mod, item.context_expr, state,
                                       findings)
                self._scan_block(mod, stmt.body, state, findings, fn)
                continue
            if isinstance(stmt, ast.Try):
                self._scan_block(mod, stmt.body, state, findings, fn)
                for h in stmt.handlers:
                    self._scan_block(mod, h.body, dict(state),
                                     findings, fn)
                self._scan_block(mod, stmt.finalbody, state, findings,
                                 fn)
                continue
            # plain statement: consumptions in the value happen BEFORE
            # the rebinding takes effect (`key, sub = split(key)`)
            self._consume_expr(mod, stmt, state, findings)
            if isinstance(stmt, (ast.Assign, ast.AnnAssign,
                                 ast.AugAssign)):
                for name in self._targets(stmt):
                    state.pop(name, None)

    def _consume_expr(self, mod, expr, state, findings):
        for var, call in self._consumptions(mod, expr):
            if var in state:
                findings.append(self.finding(
                    mod, call,
                    f"key {var!r} already consumed at line "
                    f"{state[var]}; reusing it makes both draws "
                    f"bit-identical",
                ))
            else:
                state[var] = call.lineno


@register
class TracerLeakRule(Rule):
    """Assigning a traced intermediate to ``self.*`` or a global inside
    a jit-traced function smuggles a tracer out of the trace: the
    attribute holds a tracer (crashing later uses), or — with a
    concrete-looking value — silently pins stale state from trace
    time."""

    name = "tracer-leak"
    summary = ("traced value assigned to self.*/globals inside a "
               "jitted function")
    hint = ("return the value and let the CALLER store it (the engine "
            "pattern: `self.pos, ... = _chunk_step(...)`)")

    def check(self, mod: ModuleInfo, config: AnalysisConfig
              ) -> Iterable[Finding]:
        jitted: list[ast.FunctionDef] = []
        for fn in _functions(mod.tree):
            for dec in fn.decorator_list:
                dec_call = dec if isinstance(dec, ast.Call) else None
                if (dec_call and _is_jit_constructor(mod, dec_call)) \
                        or mod.resolve(dec) in _JIT_NAMES:
                    jitted.append(fn)
                    break
        for fn in jitted:
            # nested defs (scan bodies) trace under the same jit
            for node in ast.walk(fn):
                if isinstance(node, ast.Global) and node.names:
                    yield self.finding(
                        mod, node,
                        f"global statement inside jit-traced "
                        f"{fn.name!r}: assignments leak trace-time "
                        f"values (or tracers) out of the trace",
                    )
                if not isinstance(node, (ast.Assign, ast.AugAssign,
                                         ast.AnnAssign)):
                    continue
                targets = (node.targets
                           if isinstance(node, ast.Assign)
                           else [node.target])
                for tgt in targets:
                    for sub in ast.walk(tgt):
                        if (isinstance(sub, ast.Attribute)
                                and isinstance(sub.ctx, ast.Store)
                                and isinstance(sub.value, ast.Name)
                                and sub.value.id == "self"):
                            yield self.finding(
                                mod, node,
                                f"assignment to self.{sub.attr} "
                                f"inside jit-traced {fn.name!r} "
                                f"leaks a traced intermediate",
                            )


# ---------------------------------------------------------------------------
# shardlint: SPMD collective-divergence rule family
# ---------------------------------------------------------------------------

# jax.lax SPMD collectives (``lax.psum`` spellings resolve through the
# alias table to ``jax.lax.psum``)
_LAX_COLLECTIVES = frozenset({
    "psum", "pmean", "pmax", "pmin", "ppermute", "pshuffle",
    "all_gather", "all_to_all", "psum_scatter", "pbroadcast",
})
# comm-layer / multihost collective entry points, matched by final
# name whether called through an alias (``collectives.allreduce``) or
# as a Communicator method (``comm.allreduce``): every one of these
# must be issued by ALL ranks of its axis, in the same order — which
# is exactly what makes them hazardous under rank-dependent control
# flow. Final-name matching is a lint-level heuristic; the live tree
# has no same-named non-collective methods (asserted by the CI gate
# staying at zero findings).
_COLLECTIVE_NAMES = frozenset({
    "allreduce", "all_gather", "reduce_scatter", "all_to_all",
    "pingpong", "sendrecv_ring", "broadcast", "barrier_value",
    "ring_shift", "pairwise_exchange", "ring_allreduce",
    "ring_allreduce_chunked", "ring_reduce_scatter", "ring_all_gather",
    "ring_schedule", "halo_exchange", "jacobi_step",
    "process_allgather", "sync_global_devices", "broadcast_one_to_all",
    # device-initiated fused entry points (comm/fused.py): the ring
    # runs inside a Pallas kernel, but every rank must still enter the
    # kernel in lockstep — rank-dependent control flow around these is
    # the same deadlock shape as around a host-driven collective
    "fused_allreduce", "allreduce_into", "allgather_matmul",
    "fused_permute", "fused_ring_shift",
    # serving-plane KV handoff (serving_plane/migration.py,
    # service.py, and the fused DMA pair in comm/migration_dma.py —
    # one send_migration/recv_migration protocol, three transports): a
    # migration has two parties that must agree on the
    # (kv_migration, seq) schedule — rank-dependent control flow
    # around the transfer entry points is the same desync shape the
    # runtime verifier catches at merge time
    "migrate_pages", "send_migration", "recv_migration",
}) | _LAX_COLLECTIVES

#: final names whose call result identifies the calling rank — the
#: taint sources for rank-dependent control flow
_RANK_SOURCES = frozenset({"axis_index", "process_index"})

#: permutation-consuming entry points audited by
#: ``unchecked-permutation``: ``lax.ppermute`` and its
#: device-initiated sibling ``comm.fused.fused_permute`` — both take a
#: ``(src, dst)`` pair list as their third argument, and a malformed
#: list silently corrupts data on either route
_PERMUTE_CONSUMERS = frozenset({"ppermute", "fused_permute"})


def _collective_id(mod: ModuleInfo, call: ast.Call
                   ) -> tuple[str, str, str] | None:
    """(receiver, op, axis) identity of a collective call, or None.
    ``receiver`` is the dotted prefix (``comm``, ``jax.lax``, …) so ops
    on two DIFFERENT communicators never compare equal; ``axis`` is the
    first string literal among the args when one is visible (the mesh
    axis for ``lax.p*`` forms)."""
    name = _func_name(mod, call) or ""
    recv, _, op = name.rpartition(".")
    if op not in _COLLECTIVE_NAMES:
        return None
    axis = ""
    candidates = list(call.args) + [
        kw.value for kw in call.keywords
        if kw.arg in ("axis", "axis_name")]
    for a in candidates:
        if isinstance(a, ast.Constant) and isinstance(a.value, str):
            axis = a.value
            break
    return (recv, op, axis)


class _Unjudgeable(Exception):
    """A nested branch whose arms issue DIFFERENT collective sequences:
    the enclosing block's true sequence depends on a predicate the
    analyzer cannot resolve, so comparisons through it must abstain —
    flattening both arms (the naive walk) turns legitimate nested
    algorithm switches into false positives."""


def _collective_seq(mod: ModuleInfo, stmts
                    ) -> list[tuple[str, str, str]] | None:
    """Collective identities issued by a statement list, in evaluation
    order — the canonical form the divergence/order rules compare.
    Nested defs/lambdas are DEFERRED work, not issued here, and are
    skipped (they are scanned in their own scope). A nested branch
    whose arms agree contributes its sequence ONCE (whichever arm
    runs, the same collectives issue); arms that disagree make the
    whole block unjudgeable — returns None, and callers abstain (an
    inner rank-dependent branch is still flagged by its own scan)."""
    try:
        return _seq_block(mod, stmts)
    except _Unjudgeable:
        return None


def _seq_block(mod: ModuleInfo, stmts) -> list[tuple[str, str, str]]:
    out: list[tuple[str, str, str]] = []
    for stmt in stmts:
        if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.ClassDef)):
            continue
        if isinstance(stmt, ast.If):
            _seq_expr(mod, stmt.test, out)
            a = _seq_block(mod, stmt.body)
            b = _seq_block(mod, stmt.orelse)
            if a != b:
                raise _Unjudgeable
            out.extend(a)
        elif isinstance(stmt, (ast.For, ast.AsyncFor)):
            _seq_expr(mod, stmt.iter, out)
            out.extend(_seq_block(mod, stmt.body))
            out.extend(_seq_block(mod, stmt.orelse))
        elif isinstance(stmt, ast.While):
            _seq_expr(mod, stmt.test, out)
            out.extend(_seq_block(mod, stmt.body))
            out.extend(_seq_block(mod, stmt.orelse))
        elif isinstance(stmt, (ast.With, ast.AsyncWith)):
            for item in stmt.items:
                _seq_expr(mod, item.context_expr, out)
            out.extend(_seq_block(mod, stmt.body))
        elif isinstance(stmt, ast.Try):
            out.extend(_seq_block(mod, stmt.body))
            for h in stmt.handlers:
                out.extend(_seq_block(mod, h.body))
            out.extend(_seq_block(mod, stmt.orelse))
            out.extend(_seq_block(mod, stmt.finalbody))
        else:
            _seq_expr(mod, stmt, out)
    return out


def _seq_expr(mod: ModuleInfo, node: ast.AST,
              out: list[tuple[str, str, str]]) -> None:
    """Collectives issued by one expression/simple statement, appended
    in evaluation order. A conditional expression is the statement
    branch in miniature: agreeing arms count once, disagreeing arms
    are unjudgeable."""
    if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                         ast.ClassDef, ast.Lambda)):
        return
    if isinstance(node, ast.IfExp):
        _seq_expr(mod, node.test, out)
        a: list[tuple[str, str, str]] = []
        b: list[tuple[str, str, str]] = []
        _seq_expr(mod, node.body, a)
        _seq_expr(mod, node.orelse, b)
        if a != b:
            raise _Unjudgeable
        out.extend(a)
        return
    for child in ast.iter_child_nodes(node):
        _seq_expr(mod, child, out)
    if isinstance(node, ast.Call):
        cid = _collective_id(mod, node)
        if cid is not None:
            out.append(cid)


def _is_rank_source(mod: ModuleInfo, node: ast.AST) -> bool:
    if isinstance(node, ast.Call):
        name = _func_name(mod, node) or ""
        if name.rsplit(".", 1)[-1] in _RANK_SOURCES:
            return True
        # os.environ.get("HPCPAT_PROCESS_ID") — the launcher protocol
        if (name == "os.environ.get" and node.args
                and isinstance(node.args[0], ast.Constant)
                and "PROCESS_ID" in str(node.args[0].value)):
            return True
    if isinstance(node, ast.Subscript):
        if (mod.resolve(node.value) == "os.environ"
                and isinstance(node.slice, ast.Constant)
                and "PROCESS_ID" in str(node.slice.value)):
            return True
    return False


def _expr_rank_dependent(mod: ModuleInfo, expr: ast.AST,
                         tainted: set[str]) -> bool:
    for node in ast.walk(expr):
        if _is_rank_source(mod, node):
            return True
        if (isinstance(node, ast.Name) and isinstance(node.ctx, ast.Load)
                and node.id in tainted):
            return True
    return False


def _rank_tainted(mod: ModuleInfo, fn: ast.FunctionDef) -> set[str]:
    """Names assigned (transitively) from a rank source anywhere in
    ``fn`` — a flow-insensitive fixpoint, enough for the straight-line
    ``me = lax.axis_index(axis); if me == 0: …`` hazard shape."""
    tainted: set[str] = set()
    assigns = [n for n in ast.walk(fn)
               if isinstance(n, (ast.Assign, ast.AnnAssign, ast.AugAssign))]
    changed = True
    while changed:
        changed = False
        for a in assigns:
            if a.value is None:
                continue
            if not _expr_rank_dependent(mod, a.value, tainted):
                continue
            targets = a.targets if isinstance(a, ast.Assign) else [a.target]
            for tgt in targets:
                for sub in ast.walk(tgt):
                    if (isinstance(sub, ast.Name)
                            and sub.id not in tainted):
                        tainted.add(sub.id)
                        changed = True
    return tainted


def _ops(seq: list[tuple[str, str, str]]) -> str:
    return ", ".join(op for _, op, _ in seq) if seq else "(none)"


@register
class CollectiveDivergenceRule(Rule):
    """The deadlock class the reference's miniapps hand-dodge with
    even/odd Send/Recv ordering: SPMD ranks must issue the identical
    collective sequence, so a collective under rank-dependent control
    flow whose paths disagree — branch arms with different sequences,
    a rank-guarded early return skipping later collectives, a loop
    with a rank-sized trip count — hangs the job silently (every other
    rank waits inside a collective this rank never enters)."""

    name = "collective-divergence"
    family = "shardlint"
    summary = ("collective under rank-dependent control flow whose "
               "paths disagree on the schedule")
    hint = ("issue the same collective sequence on every rank: branch "
            "on rank for DATA (jnp.where) or host I/O, never for "
            "which collective comes next")

    def check(self, mod: ModuleInfo, config: AnalysisConfig
              ) -> Iterable[Finding]:
        for fn in _functions(mod.tree):
            tainted = _rank_tainted(mod, fn)
            yield from self._scan(mod, fn.body, tainted)

    def _scan(self, mod, stmts, tainted) -> Iterable[Finding]:
        for idx, stmt in enumerate(stmts):
            if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef,
                                 ast.ClassDef)):
                continue  # separate scope, scanned on its own
            if isinstance(stmt, ast.If):
                if _expr_rank_dependent(mod, stmt.test, tainted):
                    a = _collective_seq(mod, stmt.body)
                    b = _collective_seq(mod, stmt.orelse)
                    if a is None or b is None:
                        # an arm with an unjudgeable nested branch:
                        # abstain here — a rank-dependent inner branch
                        # is still flagged by its own scan below
                        pass
                    elif a != b:
                        yield self.finding(
                            mod, stmt,
                            f"rank-dependent branch issues different "
                            f"collective sequences: if-arm [{_ops(a)}] "
                            f"vs else-arm [{_ops(b)}] — ranks disagree "
                            f"on which collective comes next (deadlock "
                            f"shape)",
                        )
                    elif (self._returns(stmt.body)
                            != self._returns(stmt.orelse)):
                        trailing = _collective_seq(mod, stmts[idx + 1:])
                        if trailing:
                            yield self.finding(
                                mod, stmt,
                                f"rank-dependent early return skips "
                                f"{len(trailing)} later collective(s) "
                                f"([{_ops(trailing)}]) on the "
                                f"returning ranks",
                            )
                yield from self._scan(mod, stmt.body, tainted)
                yield from self._scan(mod, stmt.orelse, tainted)
            elif isinstance(stmt, (ast.For, ast.AsyncFor, ast.While)):
                bound = (stmt.test if isinstance(stmt, ast.While)
                         else stmt.iter)
                if _expr_rank_dependent(mod, bound, tainted):
                    body = _collective_seq(mod, stmt.body)
                    if body:
                        yield self.finding(
                            mod, stmt,
                            f"collective(s) [{_ops(body)}] inside a "
                            f"loop with a rank-dependent trip count — "
                            f"ranks issue different collective counts",
                        )
                yield from self._scan(mod, stmt.body, tainted)
                yield from self._scan(mod, stmt.orelse, tainted)
            elif isinstance(stmt, (ast.With, ast.AsyncWith)):
                yield from self._scan(mod, stmt.body, tainted)
            elif isinstance(stmt, ast.Try):
                for blk in (stmt.body, stmt.orelse, stmt.finalbody):
                    yield from self._scan(mod, blk, tainted)
                for h in stmt.handlers:
                    yield from self._scan(mod, h.body, tainted)

    @staticmethod
    def _returns(stmts) -> bool:
        """Whether the block unconditionally RETURNS. ``raise`` is
        exempt on purpose: an error path kills the job loudly rather
        than deadlocking it quietly (the precondition-check pattern)."""
        return bool(stmts) and isinstance(stmts[-1], ast.Return)


@register
class CollectiveOrderRule(Rule):
    """Two code paths reaching the same communicator with the same ops
    in different orders: if the branch predicate EVER disagrees across
    ranks, rank A's first collective pairs with rank B's second — the
    mis-ordered ``MPI_Send/Recv`` cross, one config drift away from a
    deadlock. Unlike ``collective-divergence`` this fires on ANY
    predicate: a reordered-but-equal op multiset has no legitimate
    reason to exist."""

    name = "collective-order"
    family = "shardlint"
    summary = ("sibling code paths issue the same collectives in "
               "different orders")
    hint = ("normalize the order so every path reaching the "
            "communicator issues the identical sequence")

    def check(self, mod: ModuleInfo, config: AnalysisConfig
              ) -> Iterable[Finding]:
        for node in ast.walk(mod.tree):
            if not isinstance(node, ast.If):
                continue
            a = _collective_seq(mod, node.body)
            b = _collective_seq(mod, node.orelse)
            if a and b and a != b and sorted(a) == sorted(b):
                yield self.finding(
                    mod, node,
                    f"if/else arms issue the same collectives in "
                    f"different orders: [{_ops(a)}] vs [{_ops(b)}] — "
                    f"should the predicate ever disagree across "
                    f"ranks, the orderings cross",
                )


@register
class UncheckedPermutationRule(Rule):
    """A malformed permutation pair list does not deadlock — XLA's
    ``ppermute`` silently zero-fills destinations with no incoming pair
    and drops duplicated sources, and the device-initiated
    ``fused_permute`` would strand a rank waiting on a DMA that never
    arrives — either way WORSE than an error: wrong data or a silent
    hang. ``comm.ring.check_permutation`` closes that gap; this rule
    makes routing every pair list through it a checked invariant for
    every consumer in ``_PERMUTE_CONSUMERS``."""

    name = "unchecked-permutation"
    family = "shardlint"
    summary = ("ppermute/fused_permute pair list built without "
               "ring.check_permutation")
    hint = ("bind the pair list to a name and run "
            "comm.ring.check_permutation(pairs, size) before the "
            "ppermute/fused_permute — a malformed permutation "
            "silently drops or duplicates data")

    def check(self, mod: ModuleInfo, config: AnalysisConfig
              ) -> Iterable[Finding]:
        checked: dict[ast.AST | None, set[str]] = {}
        permutes: list[tuple[ast.Call, str]] = []
        for node in ast.walk(mod.tree):
            if not isinstance(node, ast.Call):
                continue
            base = (_func_name(mod, node) or "").rsplit(".", 1)[-1]
            if base == "check_permutation":
                if node.args and isinstance(node.args[0], ast.Name):
                    checked.setdefault(self._scope(mod, node), set()).add(
                        node.args[0].id)
            elif base in _PERMUTE_CONSUMERS:
                permutes.append((node, base))
        for call, base in permutes:
            perm = call.args[2] if len(call.args) >= 3 else None
            if perm is None:
                for kw in call.keywords:
                    if kw.arg == "perm":
                        perm = kw.value
            if perm is None:
                continue
            if isinstance(perm, ast.Name):
                if perm.id in checked.get(self._scope(mod, call), ()):
                    continue
                msg = (f"pair list {perm.id!r} reaches {base} "
                       f"without a check_permutation in this scope")
            else:
                msg = (f"pair list built inline in the {base} call — "
                       "it can never have been check_permutation'd")
            yield self.finding(mod, call, msg)

    @staticmethod
    def _scope(mod: ModuleInfo, node: ast.AST) -> ast.AST | None:
        cur = mod.parents.get(node)
        while cur is not None and not isinstance(
                cur, (ast.FunctionDef, ast.AsyncFunctionDef)):
            cur = mod.parents.get(cur)
        return cur


@register
class SpecMismatchRule(Rule):
    """PartitionSpec literals inconsistent with the module they live
    in. Three checks: an axis name appearing twice in one spec (jax
    rejects it at run time — this catches it at review time); an axis
    name absent from the mesh axes the SAME module declares (only when
    every mesh declaration in the module is a resolvable literal — a
    module building specs for a caller-provided mesh is never judged);
    and a donated jit arg whose literal in-sharding matches no literal
    out-sharding (XLA cannot alias a resharded buffer: the donation is
    silently wasted and the input still dies)."""

    name = "spec-mismatch"
    family = "shardlint"
    summary = ("PartitionSpec inconsistent with the module's mesh "
               "axes or a donated buffer's output specs")
    hint = ("axis names in a PartitionSpec must exist on the mesh and "
            "appear at most once; a donated input must share a spec "
            "with some output for the buffer to alias")

    def check(self, mod: ModuleInfo, config: AnalysisConfig
              ) -> Iterable[Finding]:
        declared = self._declared_axes(mod)
        for node in ast.walk(mod.tree):
            if not (isinstance(node, ast.Call) and self._is_spec(mod, node)):
                continue
            axes = self._spec_axes(node)
            seen: set[str] = set()
            for ax in axes:
                if ax in seen:
                    yield self.finding(
                        mod, node,
                        f"axis {ax!r} appears twice in one "
                        f"PartitionSpec — jax rejects duplicate mesh "
                        f"axes in a spec",
                    )
                    break
                seen.add(ax)
            if declared:
                unknown = sorted(set(axes) - declared)
                if unknown:
                    yield self.finding(
                        mod, node,
                        f"PartitionSpec axis(es) "
                        f"{', '.join(map(repr, unknown))} not among "
                        f"the mesh axes declared in this module "
                        f"({', '.join(map(repr, sorted(declared)))})",
                    )
        yield from self._donation_specs(mod)

    @staticmethod
    def _is_spec(mod: ModuleInfo, call: ast.Call) -> bool:
        return ((_func_name(mod, call) or "").rsplit(".", 1)[-1]
                == "PartitionSpec")

    @staticmethod
    def _spec_axes(call: ast.Call) -> list[str]:
        """Flattened axis-name string literals of one spec call
        (``P(("dp", "fsdp"), None)`` shards one dim over two axes)."""
        out: list[str] = []
        for arg in call.args:
            for sub in ast.walk(arg):
                if isinstance(sub, ast.Constant) and isinstance(
                        sub.value, str):
                    out.append(sub.value)
        return out

    @staticmethod
    def _literal_names(arg: ast.AST) -> tuple[str, ...] | None:
        if isinstance(arg, ast.Constant) and isinstance(arg.value, str):
            return (arg.value,)
        if isinstance(arg, (ast.Tuple, ast.List)):
            vals = []
            for e in arg.elts:
                if not (isinstance(e, ast.Constant)
                        and isinstance(e.value, str)):
                    return None
                vals.append(e.value)
            return tuple(vals)
        return None

    def _declared_axes(self, mod: ModuleInfo) -> frozenset[str] | None:
        """Mesh axis names declared by this module's ``Mesh(...)`` /
        ``make_mesh({...})`` calls, or None when there are none or ANY
        declaration is non-literal (open world: a generic mesh builder
        like topology.py must not have its spec literals judged)."""
        axes: set[str] = set()
        found = False
        for node in ast.walk(mod.tree):
            if not isinstance(node, ast.Call):
                continue
            base = (_func_name(mod, node) or "").rsplit(".", 1)[-1]
            if base == "Mesh":
                arg = node.args[1] if len(node.args) > 1 else None
                for kw in node.keywords:
                    if kw.arg == "axis_names":
                        arg = kw.value
                names = self._literal_names(arg) if arg is not None else None
                if names is None:
                    return None
                axes.update(names)
                found = True
            elif base == "make_mesh":
                if not node.args:
                    return None
                shape = node.args[0]
                if not (isinstance(shape, ast.Dict) and all(
                        isinstance(k, ast.Constant)
                        and isinstance(k.value, str)
                        for k in shape.keys)):
                    return None
                axes.update(k.value for k in shape.keys)
                found = True
        return frozenset(axes) if found else None

    # -- donated-arg sharding consistency ------------------------------

    def _donation_specs(self, mod: ModuleInfo) -> Iterable[Finding]:
        for fn in _functions(mod.tree):
            for dec in fn.decorator_list:
                if not (isinstance(dec, ast.Call)
                        and _is_jit_constructor(mod, dec)):
                    continue
                kws = {kw.arg: kw.value for kw in dec.keywords}
                nums = (_int_tuple(kws["donate_argnums"])
                        if "donate_argnums" in kws else None)
                ins, outs = kws.get("in_shardings"), kws.get("out_shardings")
                if not nums or ins is None or outs is None:
                    continue
                in_specs = self._spec_list(mod, ins)
                out_specs = self._spec_list(mod, outs)
                if in_specs is None or out_specs is None:
                    continue  # non-literal shardings: not judged
                out_sigs = {sig for _, sig in out_specs}
                for i in nums:
                    if i >= len(in_specs):
                        continue
                    node, sig = in_specs[i]
                    if sig not in out_sigs:
                        yield self.finding(
                            mod, node,
                            f"donated arg {i}'s sharding matches no "
                            f"out_sharding of {fn.name!r} — the "
                            f"donation cannot alias (the buffer is "
                            f"resharded; the input still dies, the "
                            f"memory saving silently doesn't happen)",
                        )

    def _spec_list(self, mod: ModuleInfo, node: ast.AST
                   ) -> list[tuple[ast.AST, tuple]] | None:
        """[(anchor node, positional spec signature)] from a literal
        tuple/list of ``P(...)``/``NamedSharding(mesh, P(...))``
        entries (a bare call counts as a 1-tuple); None if any entry
        is not a recognizable literal."""
        elts = (node.elts if isinstance(node, (ast.Tuple, ast.List))
                else [node])
        out = []
        for e in elts:
            entry = self._spec_entry(mod, e)
            if entry is None:
                return None
            out.append(entry)
        return out

    def _spec_entry(self, mod: ModuleInfo, node: ast.AST
                    ) -> tuple[ast.AST, tuple] | None:
        if not isinstance(node, ast.Call):
            return None
        base = (_func_name(mod, node) or "").rsplit(".", 1)[-1]
        if base == "NamedSharding" and len(node.args) >= 2:
            inner = node.args[1]
            if isinstance(inner, ast.Call) and self._is_spec(mod, inner):
                sig = self._spec_signature(inner)
                return None if sig is None else (node, sig)
            return None
        if self._is_spec(mod, node):
            sig = self._spec_signature(node)
            return None if sig is None else (node, sig)
        return None

    @staticmethod
    def _spec_signature(call: ast.Call) -> tuple | None:
        """Positional (axis-or-None, ...) signature of a spec literal;
        None when any element is not a literal."""
        sig: list = []
        for arg in call.args:
            if isinstance(arg, ast.Constant) and (
                    arg.value is None or isinstance(arg.value, str)):
                sig.append(arg.value)
            elif isinstance(arg, (ast.Tuple, ast.List)):
                elems = []
                for e in arg.elts:
                    if not (isinstance(e, ast.Constant)
                            and isinstance(e.value, str)):
                        return None
                    elems.append(e.value)
                sig.append(tuple(elems))
            else:
                return None
        return tuple(sig)
