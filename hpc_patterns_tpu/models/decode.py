"""Autoregressive decoding with a KV cache for the flagship model.

Completes the model lifecycle (train → checkpoint → serve): a batched
``prefill`` over the prompt, then a jitted single-token ``decode_step``
against a static-shape KV cache, composed by ``greedy_generate`` into a
``lax.scan`` decode loop — no data-dependent Python control flow, one
compilation for the whole generation (the XLA ground rule).

TPU-shaped choices:

- the cache is per-layer (batch, kv_heads, max_len, head_dim) buffers
  in the compute dtype (or int8 + per-row scales, kv_cache_dtype) —
  KERNEL layout, sequence contiguous per (batch, kv
  head) row — written in place with ``dynamic_update_slice`` under a
  donated jit; steady-state HBM traffic is the cache read, not a
  re-materialization;
- grouped-query attention pays off here: the cache stores ``kv_heads``
  (not ``n_heads``) heads, and decode attends with GROUPED queries
  against the unexpanded cache — both the memory and the per-step
  bandwidth saving GQA exists for;
- decode attention is the flash-decode Pallas kernel by default
  (ops/flash_decode.py): one streamed pass over the cache whose HBM
  traffic is proportional to the fill POSITION (blocks past it are
  never fetched — clamped index map), vs the XLA gather path that
  reads all of max_len and masks. ``cfg.decode_attn = "gather"`` keeps
  the einsum path: position masking over the full cache, static
  shapes — the partitioning-friendly form sharded (tp) serving needs
  (GSPMD splits einsums; it cannot split a pallas_call);
- MoE decode routes drop-free (capacity = token count): training-time
  capacity drops are load-balance pressure over B·T competing tokens,
  which a decode step doesn't have — and serving must never drop a
  token.

Params are shared verbatim with transformer.forward; under a mesh with
Megatron-sharded params, GSPMD partitions these einsums the same way
(no decode-specific annotations needed for tp).
"""

from __future__ import annotations

import warnings
import weakref
from functools import partial

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import PartitionSpec as P

from hpc_patterns_tpu.harness import trace as tracelib
from hpc_patterns_tpu.models.sharding_util import mesh_axis_size, resolve_spec
from hpc_patterns_tpu.topology import shard_map
from hpc_patterns_tpu.models.transformer import (
    TransformerConfig,
    _rmsnorm,
    apply_rope,
    matmul_weight,
    project_qkv,
)
from hpc_patterns_tpu.parallel.ring_attention import full_attention


def _tp_size(mesh, cfg: TransformerConfig) -> int:
    return mesh_axis_size(mesh, cfg.axis_tp) if mesh is not None else 1


def _flash_partition(mesh, cfg: TransformerConfig) -> bool:
    """Can the Pallas decode kernels run tp-sharded on this mesh?

    GSPMD partitions einsums but not a ``pallas_call`` — the round-3
    limitation that forced sharded serving onto the gather path. The
    kernels' head axes are embarrassingly parallel though, so a
    ``shard_map`` manual partition over ``axis_tp`` (contiguous head
    blocks: q head k·g+j stays with kv head k) recovers the flash
    kernels under tp whenever tp divides kv_heads. Returns False (with
    a warning) when it cannot, and the caller keeps the gather path.
    """
    tp = _tp_size(mesh, cfg)
    if tp <= 1:
        return False
    if cfg.kv_heads % tp:
        warnings.warn(
            f"decode: tp size {tp} does not divide kv_heads "
            f"{cfg.kv_heads}; decode_attn='flash' falls back to the "
            "gather path (shard_map needs whole kv-head blocks per "
            "rank) — use a tp that divides kv_heads to keep the kernel",
            stacklevel=3,
        )
        return False
    return True


def _tp_serving_specs(mesh, cfg: TransformerConfig):
    """The ONE definition of the tp manual-partition layout shared by
    the linear and paged kernel routes: ``(row3, block4)`` — 3-D leaves
    (q (B, H, Dh); linear scale rows (B, Hkv, len)) shard dim 1, 4-D
    leaves (linear cache, page pools, scale pools) shard dim 1. Head
    blocks are contiguous, so q head k·g+j stays with kv head k."""
    from jax.sharding import PartitionSpec as PS

    tp = cfg.axis_tp
    return (resolve_spec(PS(None, tp, None), mesh, cfg.mesh_axes),
            resolve_spec(PS(None, tp, None, None), mesh, cfg.mesh_axes))


def _tp_pin_cache(cache, mesh, cfg: TransformerConfig):
    """Constrain every cache/pool leaf kv-head-sharded over tp (dim 1;
    3-D or 4-D leaves — the layout both sharded decode routes consume
    in place). Non-array entries (the page table) pass through."""
    from jax.sharding import NamedSharding

    row3, block4 = _tp_serving_specs(mesh, cfg)
    sh = {3: NamedSharding(mesh, row3), 4: NamedSharding(mesh, block4)}

    def pin(a):
        return (lax.with_sharding_constraint(a, sh[a.ndim])
                if hasattr(a, "ndim") and a.ndim in sh else a)

    return jax.tree.map(pin, cache)


def _flash_route(mesh, cfg: TransformerConfig):
    """(use_flash, flash_sharded): the ONE flash/gather routing decision
    shared by prefill and decode_step — the prompt pass and the step
    pass must always take the same route under the same mesh."""
    flash_sharded = (cfg.decode_attn == "flash"
                     and _flash_partition(mesh, cfg))
    use_flash = cfg.decode_attn == "flash" and (
        _tp_size(mesh, cfg) <= 1 or flash_sharded
    )
    return use_flash, flash_sharded


#: KV storage dtypes carrying per-row dequant scales (the quantized
#: cache family; "compute" stores the model dtype scale-free)
KV_QUANTIZED = ("int8", "fp8")

#: float8_e4m3fn's largest finite value — the fp8 analog of int8's 127
FP8_MAX = 448.0


def _kv_quantized(cfg: TransformerConfig) -> bool:
    return cfg.kv_cache_dtype in KV_QUANTIZED


def _kv_storage_dtype(cfg: TransformerConfig):
    """The dtype KV bytes are STORED in: the compute dtype, int8, or
    float8_e4m3fn — one byte per element for both quantized forms, so
    the pool-byte win is identical; fp8 trades int8's uniform grid for
    a floating one (more headroom inside a row's dynamic range).
    Backends without fp8 support surface through
    :func:`hpc_patterns_tpu.dtypes.supports_fp8` — callers (the
    serving CLIs) degrade to int8 with a note instead of hitting a
    deep XLA lowering error."""
    if cfg.kv_cache_dtype == "int8":
        return jnp.int8
    if cfg.kv_cache_dtype == "fp8":
        return jnp.float8_e4m3fn
    return jnp.dtype(cfg.dtype)


def _quantize_rows(x, kv_dtype: str = "int8"):
    """Per-row symmetric quantization of (..., D) rows: returns
    (quantized values, f32 scales shaped (...,)) with x ~= q * scale.
    ``kv_dtype``: "int8" (round-to-nearest onto the +-127 integer
    grid) or "fp8" (scale the row's amax onto float8_e4m3fn's +-448
    range and let the float cast do the rounding)."""
    amax = jnp.max(jnp.abs(x.astype(jnp.float32)), axis=-1)
    if kv_dtype == "fp8":
        scale = jnp.maximum(amax / FP8_MAX, 1e-8)
        q = (x.astype(jnp.float32)
             / scale[..., None]).astype(jnp.float8_e4m3fn)
    else:
        scale = jnp.maximum(amax / 127.0, 1e-8)
        q = jnp.clip(jnp.round(x.astype(jnp.float32) / scale[..., None]),
                     -127, 127).astype(jnp.int8)
    return q, scale


def _dequant(cache, scale):
    return cache.astype(jnp.float32) * scale[..., None].astype(jnp.float32)


def init_cache(cfg: TransformerConfig, batch: int, max_len: int):
    """Zeroed KV cache: {"k","v"}: PER-LAYER tuples of (B, kv_heads,
    max_len, head_dim) in the compute dtype — or the one-byte storage
    dtype when cfg.kv_cache_dtype is quantized ("int8"/"fp8"), with
    per-row f32 dequant scales in extra "k_scale"/"v_scale" tuples
    (B, kv_heads, max_len), halving the cache bytes vs bf16 —
    (kernel layout: the
    sequence axis contiguous per (batch, kv head) row, what
    ops/flash_decode.py streams). Per-layer arrays — not one stacked
    (L, ...) block — so each decode step's dynamic_update_slice aliases
    its own buffer inside the generation scan's carry: the step's HBM
    traffic is the attention read plus one row write, NOT a rewrite of
    the whole cache (a stacked cache driven through a layer lax.scan
    re-materializes every byte every token — measured 25 ms/token at an
    8k cache where the read cost is ~3 ms). GQA stores kv_heads only —
    the cache is n_heads/kv_heads times smaller than MHA's."""
    dt = _kv_storage_dtype(cfg)
    shape = (batch, cfg.kv_heads, max_len, cfg.head_dim)
    # independent buffers per key AND per layer: sharing one zeros tuple
    # would alias k and v, and a donated jit would then double-donate
    # each buffer (silent copy fallback — exactly the in-place update
    # this layout exists for)
    fresh = lambda sh, d: tuple(jnp.zeros(sh, d)
                                for _ in range(cfg.n_layers))
    cache = {"k": fresh(shape, dt), "v": fresh(shape, dt)}
    if _kv_quantized(cfg):
        # per-row dequant scales ride alongside (tiny: D times smaller)
        cache["k_scale"] = fresh(shape[:-1], jnp.float32)
        cache["v_scale"] = fresh(shape[:-1], jnp.float32)
    return cache


def _mlp(x, lp, cfg: TransformerConfig):
    dt = x.dtype
    h = _rmsnorm(x, lp["ln2_scale"])
    if cfg.n_experts:
        from hpc_patterns_tpu.parallel import moe

        *lead, D = h.shape
        flat = h.reshape(-1, D)
        # capacity = token count: serving never drops a token. The
        # training forward's capacity_factor drops are a TRAINING
        # behavior (load-balance pressure over B*T competing tokens);
        # a decode step has no such competition, so drop-free routing is
        # both the correct serving semantic and what makes incremental
        # decode equal a drop-free full forward (test_decode's oracle).
        # capacity = token count stays drop-free for ANY k: a token's k
        # choices hit k DISTINCT experts, so no expert can be assigned
        # more than N tokens
        y, _ = moe.moe_dense(flat, lp["router"], lp["w1"], lp["w2"],
                             capacity=flat.shape[0],
                             top_k=cfg.n_experts_top_k)
        return x + y.reshape(*lead, D).astype(dt)
    h = jax.nn.gelu(jnp.dot(h, matmul_weight(lp, "w1", dt)))
    return x + jnp.dot(h, matmul_weight(lp, "w2", dt))


def prefill(params, prompt, cfg: TransformerConfig, max_len: int,
            mesh=None, last_pos=None):
    """Run the prompt in one batched pass (MXU-shaped, exactly
    transformer.forward's math) while capturing each layer's K/V into a
    fresh cache. Returns (last_logits (B, V) f32, cache).

    ``max_len`` sizes the static cache (prompt + planned new tokens,
    <= cfg.max_seq). ``mesh``: tp-sharded serving — the flash prefill
    kernel runs shard_mapped over ``cfg.axis_tp`` and the captured
    cache is constrained kv-head-sharded over tp (what the sharded
    decode steps consume in place).

    ``last_pos``: the BUCKETED-prompt route. A prompt right-padded to a
    bucket length compiles once per bucket instead of once per distinct
    length; causality makes positions < true length independent of the
    padding, so the K/V prefix is exact and only the returned logits
    need redirecting — ``last_pos`` (traced scalar or (B,) int32) picks
    which position's logits come back (default: the last). Padding K/V
    is garbage the caller's position cursor masks until generation
    overwrites it — the same stale-row invariant speculative decoding
    relies on."""
    B, T = prompt.shape
    use_flash, flash_sharded = _flash_route(mesh, cfg)
    if not 0 < T <= max_len <= cfg.max_seq:
        raise ValueError(
            f"need 0 < prompt len {T} <= max_len {max_len} <= "
            f"max_seq {cfg.max_seq}"
        )
    dt = jnp.dtype(cfg.dtype)
    x = params["embed"].astype(dt)[prompt]
    if cfg.pos_embed == "learned":
        x = x + params["pos_embed"].astype(dt)[:T]

    def body(h, lp):
        hn = _rmsnorm(h, lp["ln1_scale"])
        q, k, v = project_qkv(hn, lp, cfg)
        if cfg.pos_embed == "rope":
            # the cache stores POST-rope K: a key's rotation depends
            # only on its own (fixed) position, so decode steps never
            # re-rotate history
            pos = jnp.arange(T, dtype=jnp.int32)
            q = apply_rope(q, pos, cfg)
            k = apply_rope(k, pos, cfg)
        # long prompts go through the flash kernel (the dense oracle
        # materializes the (T, T) scores — an 8k-token prompt would be
        # a 17 GB allocation at B=8); short/ragged prompts and sharded
        # (gather-mode) serving keep the einsum path, which consumes
        # the narrow GQA K/V directly
        if use_flash and T % 128 == 0:
            from hpc_patterns_tpu.ops import flash_attention

            if flash_sharded:
                hspec = resolve_spec(P(None, None, cfg.axis_tp, None),
                                     mesh, cfg.mesh_axes)
                o = shard_map(
                    partial(flash_attention, causal=True), mesh=mesh,
                    in_specs=(hspec, hspec, hspec), out_specs=hspec,
                    check_vma=False,  # pallas_call can't declare vma
                )(q, k, v)
            else:
                o = flash_attention(q, k, v, causal=True)
        else:
            o = full_attention(q, k, v, causal=True)
        o = jnp.dot(o.reshape(B, T, cfg.d_model),
                    matmul_weight(lp, "wo", dt))
        h = _mlp(h + o.astype(dt), lp, cfg)
        # capture in kernel layout (B, Hkv, T, D), padded to the static
        # cache length — one transpose at prefill, zero per decode step
        kc = jnp.einsum("bthd->bhtd", k)
        vc = jnp.einsum("bthd->bhtd", v)
        pad = [(0, 0), (0, 0), (0, max_len - T), (0, 0)]
        return h, (jnp.pad(kc, pad).astype(dt), jnp.pad(vc, pad).astype(dt))

    x, (ks, vs) = lax.scan(body, x, params["layers"])
    x = _rmsnorm(x, params["ln_f_scale"])
    if last_pos is None:
        x_last = x[:, -1]
    else:
        lp = jnp.broadcast_to(jnp.asarray(last_pos, jnp.int32), (B,))
        x_last = jnp.take_along_axis(x, lp[:, None, None], axis=1)[:, 0]
    logits = jnp.dot(x_last, matmul_weight(params, "lm_head", dt))
    L = cfg.n_layers
    if _kv_quantized(cfg):
        kvd = cfg.kv_cache_dtype
        kq, ksc = zip(*(_quantize_rows(ks[l], kvd) for l in range(L)))
        vq, vsc = zip(*(_quantize_rows(vs[l], kvd) for l in range(L)))
        cache = {
            "k": tuple(kq), "v": tuple(vq),
            "k_scale": tuple(ksc), "v_scale": tuple(vsc),
        }
    else:
        cache = {
            "k": tuple(ks[l] for l in range(L)),
            "v": tuple(vs[l] for l in range(L)),
        }
    if mesh is not None and _tp_size(mesh, cfg) > 1:
        # pin the cache kv-head-sharded over tp so the per-step
        # dynamic_update_slice and attention read stay rank-local (the
        # sharded decode step's shard_map consumes exactly this layout)
        cache = _tp_pin_cache(cache, mesh, cfg)
    return logits.astype(jnp.float32), cache


def _token_step(params, pos, tokens, cfg: TransformerConfig,
                layer_states, attend_update):
    """Shared single-token transformer skeleton: embed, the UNROLLED
    layer loop (static per-layer param slices fuse; a lax.scan would
    stack the updated caches into a fresh (L, ...) block — a full
    cache rewrite per token), final norm, lm_head. Per layer it runs
    norm → qkv → rope (the CURRENT global position; cached keys are
    already post-rope from prefill) and then delegates to
    ``attend_update(q, k_new, v_new, state) -> (o, new_state)`` — the
    cache write + attention, the ONLY part that differs between the
    linear cache (flash/gather/int8/tp routes, :func:`decode_step`)
    and the paged cache (:func:`paged_decode_step`). One skeleton, so
    the two cannot drift."""
    dt = jnp.dtype(cfg.dtype)
    B = tokens.shape[0]
    x = params["embed"].astype(dt)[tokens]  # (B, D)
    if cfg.pos_embed == "learned":
        pe = params["pos_embed"].astype(dt)
        # scalar pos: one shared row (DUS slice); ragged (B,) pos:
        # per-row gather. rope needs no branch — apply_rope broadcasts
        # either shape over the heads
        x = x + (pe[pos] if jnp.ndim(pos)
                 else lax.dynamic_slice_in_dim(pe, pos, 1, axis=0))
    new_states = []
    for l in range(cfg.n_layers):
        lp = jax.tree.map(lambda a: a[l], params["layers"])
        hn = _rmsnorm(x, lp["ln1_scale"])
        q, k_new, v_new = project_qkv(hn, lp, cfg)  # (B, H/Hkv, Dh)
        if cfg.pos_embed == "rope":
            q = apply_rope(q, pos, cfg)
            k_new = apply_rope(k_new, pos, cfg)
        # GQA grouped attention against the UNEXPANDED cache: q head
        # k*g+j (project_qkv's order) reads kv head k directly — no
        # materialized n_heads-wide repeat, so per-step HBM traffic is
        # the kv_heads-narrow cache read (the saving GQA exists for)
        o, st = attend_update(q, k_new, v_new, layer_states[l])
        o = jnp.dot(o.reshape(B, cfg.d_model).astype(dt),
                    matmul_weight(lp, "wo", dt))
        x = _mlp(x + o, lp, cfg)
        new_states.append(st)
    x = _rmsnorm(x, params["ln_f_scale"])
    logits = jnp.dot(x, matmul_weight(params, "lm_head", dt))
    return logits.astype(jnp.float32), new_states


def decode_step(params, cache, pos, tokens, cfg: TransformerConfig,
                mesh=None):
    """One token for every sequence in the batch: ``tokens`` (B,) int32
    at position ``pos`` (traced scalar — the true current length, so one
    compilation serves the whole generation). Returns
    (logits (B, V) f32, updated cache).

    ``mesh``: for tp-sharded serving with ``decode_attn="flash"`` — the
    single-query kernel runs under a ``shard_map`` manual partition
    over ``cfg.axis_tp`` (heads are embarrassingly parallel in its
    grid); all other einsums partition via GSPMD as before. Without a
    mesh, sharded params still work through pure GSPMD on the gather
    path."""
    dt = jnp.dtype(cfg.dtype)
    B = tokens.shape[0]
    scale = 1.0 / (cfg.head_dim ** 0.5)
    use_flash, flash_sharded = _flash_route(mesh, cfg)

    Hkv, g, Dh = cfg.kv_heads, cfg.n_heads // cfg.kv_heads, cfg.head_dim
    quant_cache = _kv_quantized(cfg)

    def attend_update(q, k_new, v_new, state):
        k_cache, v_cache, k_scale, v_scale = state
        if quant_cache:
            k_q, k_s = _quantize_rows(k_new, cfg.kv_cache_dtype)
            v_q, v_s = _quantize_rows(v_new, cfg.kv_cache_dtype)
            k_cache = lax.dynamic_update_slice(
                k_cache, k_q[:, :, None], (0, 0, pos, 0)
            )
            v_cache = lax.dynamic_update_slice(
                v_cache, v_q[:, :, None], (0, 0, pos, 0)
            )
            k_scale = lax.dynamic_update_slice(
                k_scale, k_s[:, :, None], (0, 0, pos)
            )
            v_scale = lax.dynamic_update_slice(
                v_scale, v_s[:, :, None], (0, 0, pos)
            )
        else:
            k_cache = lax.dynamic_update_slice(
                k_cache, k_new[:, :, None].astype(dt), (0, 0, pos, 0)
            )
            v_cache = lax.dynamic_update_slice(
                v_cache, v_new[:, :, None].astype(dt), (0, 0, pos, 0)
            )
        if use_flash:
            from hpc_patterns_tpu.ops.flash_decode import (
                flash_decode_attention,
            )

            if flash_sharded:
                # manual partition over tp: contiguous head blocks —
                # q heads [c·H/tp, ...) are exactly the g-groups of kv
                # heads [c·Hkv/tp, ...), so each rank runs the kernel
                # on its own whole (q-group, cache) rows
                spec_q, spec_c = _tp_serving_specs(mesh, cfg)
                args = [q, k_cache, v_cache,
                        jnp.asarray(pos, jnp.int32).reshape(1)]
                specs = [spec_q, spec_c, spec_c, P()]
                if quant_cache:
                    args += [k_scale, v_scale]
                    specs += [spec_q] * 2  # scale rows are 3-D too

                def local_attn(q, kc, vc, p, ks=None, vs=None):
                    return flash_decode_attention(
                        q, kc, vc, p[0], k_scale=ks, v_scale=vs,
                        scale=scale,
                    )

                o = shard_map(
                    local_attn, mesh=mesh,
                    in_specs=tuple(specs), out_specs=spec_q,
                    check_vma=False,  # pallas_call can't declare vma
                )(*args)
            else:
                o = flash_decode_attention(q, k_cache, v_cache, pos,
                                           k_scale=k_scale,
                                           v_scale=v_scale, scale=scale)
        else:
            # ONE gather attention block for both cache dtypes (an int8
            # cache dequantizes in the einsum stream — elementwise
            # producers fuse, the HBM reads stay int8).
            # precision=HIGHEST: a TPU f32 einsum at default precision
            # rounds its inputs to bf16 on the MXU; true f32 here both
            # matches the flash kernel's f32 math (greedy tokens agree
            # across impls) and is free — the step is cache-read-bound
            if quant_cache:
                kd = _dequant(k_cache, k_scale)
                vd = _dequant(v_cache, v_scale)
            else:
                kd = k_cache.astype(jnp.float32)
                vd = v_cache.astype(jnp.float32)
            qg = q.reshape(B, Hkv, g, Dh)
            s = jnp.einsum(
                "bkgd,bksd->bkgs", qg.astype(jnp.float32), kd,
                precision=lax.Precision.HIGHEST,
            ) * scale
            visible = lax.broadcasted_iota(jnp.int32, s.shape, 3) <= pos
            s = jnp.where(visible, s, -1e30)
            p = jax.nn.softmax(s, axis=-1)
            o = jnp.einsum("bkgs,bksd->bkgd", p, vd,
                           precision=lax.Precision.HIGHEST)
        return o, (k_cache, v_cache, k_scale, v_scale)

    states = [
        (cache["k"][l], cache["v"][l],
         cache["k_scale"][l] if quant_cache else None,
         cache["v_scale"][l] if quant_cache else None)
        for l in range(cfg.n_layers)
    ]
    logits, new_states = _token_step(params, pos, tokens, cfg,
                                     states, attend_update)
    new_cache = {"k": tuple(s[0] for s in new_states),
                 "v": tuple(s[1] for s in new_states)}
    if quant_cache:
        new_cache["k_scale"] = tuple(s[2] for s in new_states)
        new_cache["v_scale"] = tuple(s[3] for s in new_states)
    return logits, new_cache


def extend_step(params, cache, pos, tokens, cfg: TransformerConfig):
    """Multi-token cache extension: feed ``tokens`` (B, c) occupying
    positions ``pos .. pos+c-1`` through the model against the existing
    cache, writing their K/V and returning logits for EVERY chunk
    position — ``decode_step`` generalized from c=1. The verification
    primitive of speculative decoding (models/speculative.py): one
    batched pass scores a whole proposed chunk at large-matmul shapes
    instead of c sequential single-token steps. Causality within the
    chunk: query i attends cache rows <= pos+i. Compute-dtype caches
    only (the c=1 step covers int8 serving), and the attention is the
    GATHER form regardless of cfg.decode_attn — a c-row query block
    against the cache is partitioning-friendly XLA territory, and the
    flash-decode kernel is single-query by design; expect the usual
    f32-association differences vs sequential flash steps.

    Returns (logits (B, c, vocab) f32, updated cache).
    """
    if cfg.kv_cache_dtype != "compute":
        raise ValueError("extend_step supports compute-dtype caches only")
    dt = jnp.dtype(cfg.dtype)
    B, c = tokens.shape
    scale = 1.0 / (cfg.head_dim ** 0.5)
    x = params["embed"].astype(dt)[tokens]  # (B, c, D)
    positions = pos + jnp.arange(c, dtype=jnp.int32)
    if cfg.pos_embed == "learned":
        x = x + lax.dynamic_slice_in_dim(
            params["pos_embed"].astype(dt), pos, c, axis=0
        )

    Hkv, g, Dh = cfg.kv_heads, cfg.n_heads // cfg.kv_heads, cfg.head_dim

    def body(h, lp, k_cache, v_cache):
        hn = _rmsnorm(h, lp["ln1_scale"])
        q, k_new, v_new = project_qkv(hn, lp, cfg)  # (B, c, H/Hkv, Dh)
        if cfg.pos_embed == "rope":
            q = apply_rope(q, positions, cfg)
            k_new = apply_rope(k_new, positions, cfg)
        # chunk K/V into kernel layout rows at pos..pos+c-1
        k_cache = lax.dynamic_update_slice(
            k_cache, jnp.einsum("bchd->bhcd", k_new).astype(dt),
            (0, 0, pos, 0),
        )
        v_cache = lax.dynamic_update_slice(
            v_cache, jnp.einsum("bchd->bhcd", v_new).astype(dt),
            (0, 0, pos, 0),
        )
        qg = q.reshape(B, c, Hkv, g, Dh)
        s = jnp.einsum(
            "bckgd,bksd->bkgcs", qg.astype(jnp.float32),
            k_cache.astype(jnp.float32),
            precision=lax.Precision.HIGHEST,
        ) * scale
        # query i sees cache rows <= pos+i (its own row included)
        row_pos = lax.broadcasted_iota(jnp.int32, s.shape, 4)
        q_pos = pos + lax.broadcasted_iota(jnp.int32, s.shape, 3)
        s = jnp.where(row_pos <= q_pos, s, -1e30)
        p = jax.nn.softmax(s, axis=-1)
        o = jnp.einsum("bkgcs,bksd->bckgd", p,
                       v_cache.astype(jnp.float32),
                       precision=lax.Precision.HIGHEST)
        o = jnp.dot(o.reshape(B, c, cfg.d_model).astype(dt),
                    matmul_weight(lp, "wo", dt))
        h = _mlp(h + o, lp, cfg)
        return h, (k_cache, v_cache)

    ks, vs = [], []
    for l in range(cfg.n_layers):
        lp = jax.tree.map(lambda a: a[l], params["layers"])
        x, (k_l, v_l) = body(x, lp, cache["k"][l], cache["v"][l])
        ks.append(k_l)
        vs.append(v_l)
    x = _rmsnorm(x, params["ln_f_scale"])
    logits = jnp.dot(x, matmul_weight(params, "lm_head", dt))
    return logits.astype(jnp.float32), {"k": tuple(ks), "v": tuple(vs)}


def _topk_mask(logits, top_k: int):
    """Top-k truncation (0 = off): everything below the kth-highest
    logit goes to -inf, ties at the kth value all survive. THE single
    definition of the sampling support — _pick samples from it and the
    speculative verifier's warped distributions are built from it
    (models/speculative.py), so the two can never drift apart."""
    if top_k:
        kth = lax.top_k(logits, top_k)[0][..., -1:]
        logits = jnp.where(logits >= kth, logits, -jnp.inf)
    return logits


def _pick(logits, key, temperature, greedy: bool, top_k: int):
    """Next-token choice. ``greedy`` (static) picks the branch; the
    temperature itself stays traced so every sampling temperature
    shares one compilation. ``top_k`` (static, 0 = off) truncates to
    the k highest logits via the TPU top-k kernel (no full-vocab
    sort)."""
    if greedy:
        return jnp.argmax(logits, axis=-1).astype(jnp.int32)
    logits = _topk_mask(logits, top_k)
    return jax.random.categorical(key, logits / temperature, axis=-1).astype(
        jnp.int32
    )


def _generation_scan(step_fn, logits, cache, start_pos, new_tokens, key,
                     temperature, greedy, top_k):
    """The shared generation loop: pick the first token from the
    prefill logits, then scan ``step_fn(cache, pos, tok) -> (logits,
    cache)`` for the rest — ONE copy of the pick/scan/emit machinery
    for the linear and paged caches (a sampling change lands in both
    or neither)."""
    key, sub = jax.random.split(key)
    first = _pick(logits, sub, temperature, greedy, top_k)
    if new_tokens == 1:
        return first[:, None]

    def step(carry, _):
        cache, pos, tok, key = carry
        logits, cache = step_fn(cache, pos, tok)
        key, sub = jax.random.split(key)
        nxt = _pick(logits, sub, temperature, greedy, top_k)
        return (cache, pos + 1, nxt, key), tok

    (_, _, last, _), toks = lax.scan(
        step, (cache, jnp.int32(start_pos), first, key), None,
        length=new_tokens - 1,
    )
    return jnp.concatenate([toks.T, last[:, None]], axis=1)


@partial(jax.jit, static_argnums=(2, 3, 6, 7, 8))
def _generate_jit(params, prompt, cfg, new_tokens, key, temperature,
                  greedy, top_k, mesh=None):
    B, T = prompt.shape
    max_len = T + new_tokens
    logits, cache = prefill(params, prompt, cfg, max_len, mesh=mesh)
    return _generation_scan(
        lambda c, p, t: decode_step(params, c, p, t, cfg, mesh=mesh),
        logits, cache, T, new_tokens, key, temperature, greedy, top_k,
    )


def generate(params, prompt, cfg: TransformerConfig, new_tokens: int, *,
             key=None, temperature: float = 0.0, top_k: int = 0,
             mesh=None):
    """Continuation tokens (B, new_tokens) int32: greedy by default,
    temperature/top-k sampling when ``temperature > 0`` (``key``
    required then). One jit for prefill + the whole scan'd decode
    loop. ``mesh``: tp-sharded serving with the flash kernels (see
    :func:`decode_step`); without it, sharded params serve via GSPMD
    on the gather path."""
    if new_tokens < 1:
        raise ValueError(f"new_tokens must be >= 1, got {new_tokens}")
    if prompt.shape[1] + new_tokens > cfg.max_seq:
        raise ValueError(
            f"prompt {prompt.shape[1]} + new {new_tokens} exceeds "
            f"max_seq {cfg.max_seq}"
        )
    if temperature > 0.0 and key is None:
        raise ValueError("sampling (temperature > 0) needs a PRNG key")
    if not 0 <= top_k <= cfg.vocab:
        raise ValueError(f"top_k {top_k} outside [0, vocab]")
    if key is None:
        key = jax.random.PRNGKey(0)  # unused in greedy mode
    with tracelib.compile_watch("decode.generate", _generate_jit,
                                batch=prompt.shape[0],
                                prompt_len=prompt.shape[1],
                                new_tokens=new_tokens):
        return _generate_jit(params, prompt, cfg, new_tokens, key,
                             jnp.float32(max(temperature, 1e-6)),
                             temperature <= 0.0, int(top_k), mesh)


def greedy_generate(params, prompt, cfg: TransformerConfig,
                    new_tokens: int, *, mesh=None):
    """Greedy continuation: (B, new_tokens) int32. The oracle
    equivalence (identical to re-running forward() on the growing
    sequence each step) is the decode test's invariant."""
    return generate(params, prompt, cfg, new_tokens, mesh=mesh)


# ---------------------------------------------------------------------------
# Paged KV cache (block-table serving)
# ---------------------------------------------------------------------------

def init_paged_cache(cfg: TransformerConfig, batch: int,
                     pages_per_seq: int, page_size: int,
                     pool_pages: int | None = None, table=None):
    """Paged KV cache: per-layer page POOLS plus one page table.

    The capacity lever the linear cache cannot offer: a linear cache
    allocates ``batch x max_len`` rows up front (the declared maximum),
    a paged cache allocates ``pool_pages x page_size`` rows — sized to
    the tokens that will actually exist. Layout per layer:
    (pool_pages, kv_heads, page_size, head_dim), the page-major form
    ops/flash_decode.flash_decode_paged streams; ``table``:
    (batch, pages_per_seq) int32 page ids (default: the identity
    layout; any permutation is equally valid — the kernel indirects
    through the table, which is what makes future dynamic allocation
    policies free). With a quantized ``cfg.kv_cache_dtype`` ("int8" or
    "fp8") the pools store one byte per element plus per-row f32 scale
    pools (kernel-lane layout (pool_pages, kv_heads, 1, page_size)) —
    the two CAPACITY levers stack: quantization halves page bytes vs
    bf16 (quarters vs f32), paging frees the allocate-for-longest
    waste (docs/quantization.md)."""
    if pool_pages is None:
        pool_pages = batch * pages_per_seq
    if table is None:
        if pool_pages < batch * pages_per_seq:
            # a default table over an undersized pool would silently
            # ALIAS pages across sequences (each clobbering the others'
            # K/V); page sharing is an eviction policy, not a default —
            # callers wanting it must pass an explicit table
            raise ValueError(
                f"pool_pages {pool_pages} < batch*pages_per_seq "
                f"{batch * pages_per_seq}: the default identity table "
                "needs a page per (sequence, slot); pass an explicit "
                "table to share pages deliberately"
            )
        table = jnp.arange(batch * pages_per_seq, dtype=jnp.int32)
        table = table.reshape(batch, pages_per_seq)
    quant = _kv_quantized(cfg)
    dt = _kv_storage_dtype(cfg)
    shape = (pool_pages, cfg.kv_heads, page_size, cfg.head_dim)
    fresh = lambda sh, d: tuple(jnp.zeros(sh, d)
                                for _ in range(cfg.n_layers))
    cache = {"k": fresh(shape, dt), "v": fresh(shape, dt),
             "table": jnp.asarray(table, jnp.int32)}
    if quant:
        sshape = (pool_pages, cfg.kv_heads, 1, page_size)
        cache["k_scale"] = fresh(sshape, jnp.float32)
        cache["v_scale"] = fresh(sshape, jnp.float32)
    return cache


def paged_prefill(params, prompt, cfg: TransformerConfig, cache,
                  page_size: int, mesh=None, last_pos=None):
    """Prompt pass writing into the paged cache: the ordinary prefill
    captures K/V for the prompt (a transient sized to the PROMPT, not
    the serving maximum), then each layer's pages scatter into the pool
    through the table. Returns (last_logits, cache). ``mesh``:
    tp-sharded serving — the prefill kernel runs shard_mapped and the
    page POOLS are constrained kv-head-sharded over tp (the layout
    :func:`paged_decode_step`'s sharded route consumes in place).
    ``last_pos``: the bucketed-prompt route (see :func:`prefill`) —
    logits come from this position instead of the last, so a prompt
    right-padded to a bucket rung still answers for its true end."""
    B, T = prompt.shape
    P = page_size  # shadows the PartitionSpec alias in this scope
    t_pad = -(-T // P) * P
    n_used = t_pad // P
    table = cache["table"]
    if n_used > table.shape[1]:
        raise ValueError(
            f"prompt needs {n_used} pages; table has {table.shape[1]}"
        )
    # capture at the PROMPT length (always legal), pad to the page
    # boundary afterwards — asking prefill for t_pad would spuriously
    # trip its max_len <= cfg.max_seq guard for prompts within a page
    # of the model maximum
    logits, lin = prefill(params, prompt, cfg, T, mesh=mesh,
                          last_pos=last_pos)
    if t_pad > T:
        # pad the sequence axis of every leaf (values are 4-D, int8
        # scales 3-D)
        lin = jax.tree.map(
            lambda a: jnp.pad(
                a, [(0, 0)] * 2 + [(0, t_pad - T)] + [(0, 0)] * (a.ndim - 3)
            ),
            lin,
        )
    idx = table[:, :n_used]  # (B, n_used)
    out = {"table": table}
    for name in ("k", "v"):
        pool = list(cache[name])
        for l in range(cfg.n_layers):
            # (B, Hkv, t_pad, D) -> (B, n_used, Hkv, P, D) page blocks
            pages = jnp.einsum(
                "bhpsd->bphsd",
                lin[name][l].reshape(B, cfg.kv_heads, n_used, P,
                                     cfg.head_dim),
            )
            pool[l] = pool[l].at[idx].set(pages.astype(pool[l].dtype))
        out[name] = tuple(pool)
    if _kv_quantized(cfg):
        for name in ("k_scale", "v_scale"):
            pool = list(cache[name])
            for l in range(cfg.n_layers):
                # (B, Hkv, t_pad) -> (B, n_used, Hkv, 1, P) lane-major
                pages = jnp.einsum(
                    "bhps->bphs",
                    lin[name][l].reshape(B, cfg.kv_heads, n_used, P),
                )[:, :, :, None, :]
                pool[l] = pool[l].at[idx].set(pages)
            out[name] = tuple(pool)
    if mesh is not None and _tp_size(mesh, cfg) > 1:
        # pin every pool kv-head-sharded over tp (all pool leaves are
        # 4-D with kv_heads on dim 1, scale pools included) so the
        # per-step writes and the sharded kernel stay rank-local
        out = {k: (v if k == "table" else _tp_pin_cache(v, mesh, cfg))
               for k, v in out.items()}
    return logits, out


#: SIMD row-alignment quantum for bitwise prefill parity (see
#: :func:`paged_tail_prefill`): XLA:CPU GEMMs reproduce a row's dot
#: products bitwise across DIFFERENT total row counts only when both
#: counts are multiples of this (measured: 3-row and 1-row tails
#: diverged in ULPs from the monolithic prefill's remainder-loop rows;
#: every multiple-of-8 pairing tested matched exactly). The sharing
#: engine enforces rung/page alignment to it at construction.
PREFIX_ALIGN = 8


def paged_tail_prefill(params, tail, cfg: TransformerConfig, cache,
                       page_size: int, n_prefix_pages: int, mesh=None,
                       last_pos=None):
    """Prefill ONLY the tail of a prompt whose first ``n_prefix_pages``
    pages of K/V already sit in the pool (the prefix-sharing arena's
    admission path, models/serving.py): positions ``[M, M + c)`` with
    ``M = n_prefix_pages * page_size`` are computed and scattered into
    the pages ``table[:, n_prefix:]``; the prefix pages are GATHERED as
    attention context and never written. Returns ``(logits, cache)``
    like :func:`paged_prefill`, with ``last_pos`` TAIL-RELATIVE (the
    true last token's offset into ``tail``).

    BITWISE PARITY CONTRACT (the prefix-cache oracle rides on it): the
    written tail pages and the returned logits are bit-identical to a
    monolithic :func:`paged_prefill` of the full ``M + c`` prompt,
    provided (a) the prefix pages hold bytes a SAME-LENGTH monolithic
    prefill wrote (rung-keyed sharing — prefix K/V is bitwise
    suffix-independent under causal masking, but NOT length-independent:
    prefill(32) and prefill(40) disagree in ULPs on shared rows), (b)
    ``M`` and ``c`` are multiples of :data:`PREFIX_ALIGN` (SIMD-stable
    GEMM row counts), and (c) the monolithic side took the einsum
    attention route (``full_attention``), which this function mirrors
    term for term — same grouped-score/grouped-pv einsums, same mask
    constant, same softmax axis length ``M + c``.

    Quantized KV pools (``kv_cache_dtype`` "int8"/"fp8") are refused:
    the monolithic prefill attends to the EXACT K/V and quantizes only
    for storage, so a tail computed from dequantized prefix pages
    could not be bit-equal."""
    if _kv_quantized(cfg):
        raise ValueError(
            f"paged_tail_prefill: kv_cache_dtype="
            f"{cfg.kv_cache_dtype!r} pools cannot share prefixes "
            "bitwise — the monolithic prefill attends to exact K/V "
            "and quantizes only for storage, so a tail computed from "
            "dequantized shared pages would diverge in ULPs and break "
            "the parity contract; serve quantized KV with "
            "prefix_cache=False (or keep sharing on a compute-dtype "
            "pool) — docs/quantization.md")
    from hpc_patterns_tpu.parallel.ring_attention import (
        _NEG_INF,
        _grouped_pv,
        _grouped_scores,
    )

    B, c = tail.shape
    if B != 1:
        raise ValueError(
            f"paged_tail_prefill is single-row (got B={B}): the "
            "prefix context gathers through table[0], so rows with "
            "different chains would all attend row 0's pages — batch "
            "callers must map per row")
    P = page_size  # shadows the PartitionSpec alias in this scope
    M = n_prefix_pages * P
    if M % PREFIX_ALIGN or c % PREFIX_ALIGN:
        raise ValueError(
            f"paged_tail_prefill needs prefix length {M} and tail "
            f"length {c} aligned to {PREFIX_ALIGN} rows (bitwise GEMM "
            "row stability); pad the rung/page geometry")
    table = cache["table"]
    n_tail = -(-c // P)
    if n_prefix_pages + n_tail > table.shape[1]:
        raise ValueError(
            f"tail needs pages {n_prefix_pages}..{n_prefix_pages + n_tail}"
            f"; table has {table.shape[1]}")
    dt = jnp.dtype(cfg.dtype)
    pidx = table[0, :n_prefix_pages]

    def gather_ctx(pool):
        # prefix pages -> (1, M, Hkv, D), the s-major "bskd" layout the
        # grouped-score einsum consumes (pure layout moves, bit-neutral)
        return jnp.einsum("phsd->pshd", pool[pidx]).reshape(
            1, M, cfg.kv_heads, cfg.head_dim)

    pk = jnp.stack([gather_ctx(cache["k"][l])
                    for l in range(cfg.n_layers)])
    pv = jnp.stack([gather_ctx(cache["v"][l])
                    for l in range(cfg.n_layers)])

    x = params["embed"].astype(dt)[tail]
    pos = M + jnp.arange(c, dtype=jnp.int32)
    if cfg.pos_embed == "learned":
        x = x + params["pos_embed"].astype(dt)[pos]
    scale = 1.0 / (cfg.head_dim ** 0.5)

    def body(h, layer):
        lp, pkl, pvl = layer
        hn = _rmsnorm(h, lp["ln1_scale"])
        q, k, v = project_qkv(hn, lp, cfg)
        if cfg.pos_embed == "rope":
            q = apply_rope(q, pos, cfg)
            k = apply_rope(k, pos, cfg)
        # context axis = M + c, the SAME softmax reduction length the
        # monolithic prefill used — the mask's exact zeros are the only
        # difference, and only at positions both sides zero out
        k_ctx = jnp.concatenate([pkl.astype(dt), k], axis=1)
        v_ctx = jnp.concatenate([pvl.astype(dt), v], axis=1)
        s = _grouped_scores(q, k_ctx, scale)
        t_idx = lax.broadcasted_iota(jnp.int32, s.shape, 2) + M
        s_idx = lax.broadcasted_iota(jnp.int32, s.shape, 3)
        s = jnp.where(s_idx <= t_idx, s, _NEG_INF)
        p = jax.nn.softmax(s, axis=-1)
        o = jnp.einsum("bhtd->bthd", _grouped_pv(p, v_ctx)).astype(
            q.dtype)
        o = jnp.dot(o.reshape(B, c, cfg.d_model),
                    matmul_weight(lp, "wo", dt))
        h = _mlp(h + o.astype(dt), lp, cfg)
        kc = jnp.einsum("bthd->bhtd", k)
        vc = jnp.einsum("bthd->bhtd", v)
        return h, (kc.astype(dt), vc.astype(dt))

    x, (ks, vs) = lax.scan(body, x, (params["layers"], pk, pv))
    x = _rmsnorm(x, params["ln_f_scale"])
    if last_pos is None:
        x_last = x[:, -1]
    else:
        lp_ = jnp.broadcast_to(jnp.asarray(last_pos, jnp.int32), (B,))
        x_last = jnp.take_along_axis(x, lp_[:, None, None], axis=1)[:, 0]
    logits = jnp.dot(x_last, matmul_weight(params, "lm_head",
                                           dt)).astype(jnp.float32)

    # scatter the tail pages exactly as paged_prefill does: pad the
    # tail K/V to the page boundary with zeros (the monolithic path's
    # jnp.pad bytes), page-blocked through the table
    t_pad = n_tail * P
    idx = table[:, n_prefix_pages:n_prefix_pages + n_tail]
    out = {"table": table}
    for name, lin in (("k", ks), ("v", vs)):
        pool = list(cache[name])
        for l in range(cfg.n_layers):
            linl = lin[l]
            if t_pad > c:
                linl = jnp.pad(
                    linl, [(0, 0), (0, 0), (0, t_pad - c), (0, 0)])
            pages = jnp.einsum(
                "bhpsd->bphsd",
                linl.reshape(B, cfg.kv_heads, n_tail, P, cfg.head_dim))
            pool[l] = pool[l].at[idx].set(pages.astype(pool[l].dtype))
        out[name] = tuple(pool)
    if mesh is not None and _tp_size(mesh, cfg) > 1:
        out = {k_: (v_ if k_ == "table"
                    else _tp_pin_cache(v_, mesh, cfg))
               for k_, v_ in out.items()}
    return logits, out


# tables already verified as identity layout, keyed by id() (jax arrays
# compare elementwise, so set membership is unusable); WeakValue so a
# collected table's id can never alias a new object
_identity_verified: "weakref.WeakValueDictionary[int, object]" = (
    weakref.WeakValueDictionary()
)


def _pool_write(pool, page_ids, page, offset, rows, pages: int,
                identity: bool):
    """Write one (B, Hkv, D) K/V row into its page slot. The general
    form is a scatter (pages anywhere in the pool) — correct for ANY
    table but XLA materializes a pool copy per step. With the default
    identity layout (page j of sequence b at pool row b·pages + j,
    ``pages`` = the TABLE's pages_per_seq) AND an exact-size pool, the
    write is a pure ``dynamic_update_slice`` on a (B, pages, ...) view
    — aliased in place through the generation scan, the same
    no-rematerialization property the linear cache's DUS has. An
    OVERSIZED pool makes the view layout disagree with the table's row
    numbering, so it falls through to the scatter."""
    B = rows.shape[0]
    if identity and pool.shape[0] == B * pages:
        n_pool, Hkv, P, D = pool.shape
        v = pool.reshape(B, pages, Hkv, P, D)
        v = lax.dynamic_update_slice(
            v, rows[:, None, :, None, :].astype(pool.dtype),
            (0, page, 0, offset, 0),
        )
        return v.reshape(pool.shape)
    return pool.at[page_ids, :, offset, :].set(rows.astype(pool.dtype))


def _scale_write(pool, page_ids, page, offset, rows, pages: int,
                 identity: bool):
    """int8 companion of :func:`_pool_write` for the (pool_pages,
    kv_heads, 1, page_size) lane-major scale pools: one (B, kv_heads)
    scale row lands at lane ``offset`` of its page."""
    B = rows.shape[0]
    if identity and pool.shape[0] == B * pages:
        v = pool.reshape(B, pages, *pool.shape[1:])
        v = lax.dynamic_update_slice(
            v, rows[:, None, :, None, None].astype(pool.dtype),
            (0, page, 0, 0, offset),
        )
        return v.reshape(pool.shape)
    return pool.at[page_ids, :, 0, offset].set(rows.astype(pool.dtype))


def _paged_attend_gather(q, k_pool, v_pool, ks_pool, vs_pool, table,
                         pos, cfg: TransformerConfig, scale):
    """The pure-XLA paged attention step (``cfg.decode_attn ==
    "gather"``): each row's pages gather through the table into a
    contiguous (B, Hkv, pages·P, D) view and the step is
    :func:`decode_step`'s gather block — one fused mask+softmax pass,
    past-the-fill positions (pad pages, trash entries) masked by the
    position cursor. This is the serving route off-TPU: a pallas_call
    runs in INTERPRET mode there, paying per-grid-point host cost that
    scales with batch × kv_heads (measured ~10x a decode step on the
    8-device CPU mesh at serving widths); it also partitions via plain
    GSPMD under tp, where the kernel needs a shard_map. On TPU the
    kernel remains the default — its clamped index map reads
    position-proportional bytes; this view reads the full allocation.
    ``pos``: scalar or ragged (B,); int8 pools dequantize in the einsum
    stream like the linear gather."""
    B, pages = table.shape
    Hkv, g, Dh = cfg.kv_heads, cfg.n_heads // cfg.kv_heads, cfg.head_dim
    P = k_pool.shape[2]
    quant = ks_pool is not None

    def view(pool):  # (pool, Hkv, P, D) -> (B, Hkv, pages*P, D)
        gat = pool[table]  # (B, pages, Hkv, P, D)
        return jnp.einsum("bphsd->bhpsd", gat).reshape(
            B, Hkv, pages * P, Dh).astype(jnp.float32)

    def scale_view(pool):  # (pool, Hkv, 1, P) -> (B, Hkv, pages*P)
        gat = pool[table][:, :, :, 0, :]  # (B, pages, Hkv, P)
        return jnp.einsum("bphs->bhps", gat).reshape(B, Hkv, pages * P)

    kd, vd = view(k_pool), view(v_pool)
    if quant:
        kd = kd * scale_view(ks_pool)[..., None]
        vd = vd * scale_view(vs_pool)[..., None]
    qg = q.reshape(B, Hkv, g, Dh)
    s = jnp.einsum("bkgd,bksd->bkgs", qg.astype(jnp.float32), kd,
                   precision=lax.Precision.HIGHEST) * scale
    idx = lax.broadcasted_iota(jnp.int32, s.shape, 3)
    visible = idx <= (pos[:, None, None, None] if jnp.ndim(pos)
                      else pos)
    s = jnp.where(visible, s, -1e30)
    p = jax.nn.softmax(s, axis=-1)
    o = jnp.einsum("bkgs,bksd->bkgd", p, vd,
                   precision=lax.Precision.HIGHEST)
    return o.reshape(B, cfg.n_heads, Dh)


def paged_decode_step(params, cache, pos, tokens, cfg: TransformerConfig,
                      identity_layout: bool = False, mesh=None,
                      pages_per_step: int | None = None):
    """One token per sequence against the paged cache: the new K/V row
    scatters into page ``table[:, pos // P]`` at offset ``pos % P``,
    and attention streams the live pages through
    ops/flash_decode.flash_decode_paged. ``pos``: a shared scalar
    cursor (like decode_step) OR a (B,) vector of per-sequence
    positions — RAGGED serving, every sequence at its own length (the
    kernel masks and clamps per row; rope/learned embeddings gather
    per row; the cache write scatters per-row offsets).
    ``cfg.decode_attn`` routes the attention like the linear step:
    "flash" (default) streams live pages through the pallas kernel;
    "paged_flash" gathers the live pages into VMEM through the table
    and runs the exact-softmax paged kernel
    (:func:`~hpc_patterns_tpu.ops.paged_attention.
    paged_attention_decode` — bitwise the gather route's math on
    compute-dtype pools, in-kernel dequant of int8/fp8);
    "gather" takes :func:`_paged_attend_gather` — the pure-XLA view
    that serving uses off-TPU (a pallas_call interprets per grid point
    there) and that partitions via GSPMD under any tp. ``mesh``:
    tp-sharded paged serving — the paged kernel runs under a shard_map
    manual partition over ``cfg.axis_tp`` (whole kv-head blocks per
    rank, like the linear route; tp must divide kv_heads), pools enter
    kv-head-sharded (``paged_prefill(..., mesh=...)``'s layout) and
    the pool writes partition via GSPMD. ``identity_layout`` (static):
    promise that the table is the default identity layout, enabling
    the in-place DUS write for the scalar-cursor case (ragged writes
    always scatter; see :func:`_pool_write`).

    CONTRACT: every position < pages_per_seq * page_size — the caller
    owns the capacity check (:func:`paged_generate` guards it). A
    CONCRETE ``pos`` (eager call) is checked here and raises past
    capacity; a traced ``pos`` (inside jit) cannot be — past-capacity
    steps then clamp to the LAST page (``jnp.take``'s mode) and
    silently corrupt its history."""
    P = cache["k"][0].shape[2]
    table = cache["table"]
    scale = 1.0 / (cfg.head_dim ** 0.5)
    ragged = jnp.ndim(pos) == 1

    # identity_layout is a static promise the tracer cannot check — but
    # when the caller hands a CONCRETE table (direct API use outside
    # jit), verify it eagerly before trusting the DUS fast path: a
    # permuted table plus an exact-size pool would write to the wrong
    # pool rows and silently corrupt other sequences' K/V. (The internal
    # _paged_generate_jit caller builds the identity table itself.)
    # Ragged steps always scatter (ident below), so the promise is
    # inert there; the check memoizes per table OBJECT so an eager
    # serving loop reusing one table pays the host compare once, not
    # per token.
    if (identity_layout and not ragged
            and not isinstance(table, jax.core.Tracer)
            and cache["k"][0].shape[0] == table.shape[0] * table.shape[1]
            and _identity_verified.get(id(table)) is not table):
        expect = np.arange(table.size, dtype=np.int32).reshape(table.shape)
        if not np.array_equal(np.asarray(table), expect):
            raise ValueError(
                "identity_layout=True but cache['table'] is not the "
                "identity layout over an exact-size pool — the in-place "
                "DUS write would corrupt other sequences' K/V; drop the "
                "flag (scatter path) or use the default table"
            )
        _identity_verified[id(table)] = table
    # pos is usually traced (the caller owns the capacity check, see
    # the contract below) — but an eager/concrete pos CAN be checked,
    # and ragged direct callers are exactly who hits this
    if not isinstance(pos, jax.core.Tracer):
        if np.any(np.asarray(pos) >= table.shape[1] * P):
            raise ValueError(
                f"position(s) {np.asarray(pos).max()} past cache "
                f"capacity {table.shape[1] * P} tokens: past-capacity "
                "writes clamp to the last page and corrupt its history"
            )

    from hpc_patterns_tpu.ops.flash_decode import flash_decode_paged

    page = pos // P  # scalar, or (B,) per-sequence page index
    if ragged:
        page_ids = jnp.take_along_axis(
            table, page[:, None], axis=1
        )[:, 0]  # (B,) — each row its own column
    else:
        page_ids = jnp.take(table, page, axis=1)  # (B,)
    offset = pos % P

    quant = _kv_quantized(cfg)
    ident = identity_layout and not ragged
    pages = table.shape[1]
    tp = _tp_size(mesh, cfg)
    # THE paged routing decision (one place, like _flash_route on the
    # linear path): "flash" streams pages through flash_decode_paged,
    # "paged_flash" gathers them into VMEM through the table and runs
    # the exact-softmax kernel (ops/paged_attention.py — bitwise the
    # gather route's math on compute-dtype pools, in-kernel dequant on
    # quantized ones), "gather" is the pure-XLA view. Both kernels
    # shard_map over tp with whole kv-head blocks per rank.
    kernel_route = cfg.decode_attn if cfg.decode_attn in (
        "flash", "paged_flash") else None
    if kernel_route and tp > 1 and cfg.kv_heads % tp:
        raise ValueError(
            f"paged tp serving needs tp {tp} to divide kv_heads "
            f"{cfg.kv_heads} (whole kv-head blocks per rank) — or "
            "decode_attn='gather', which partitions via GSPMD"
        )
    paged_sharded = kernel_route is not None and tp > 1
    if kernel_route == "paged_flash":
        from hpc_patterns_tpu.ops.paged_attention import (
            paged_attention_decode,
        )

        def kernel_fn(q, kp, vp, tbl, p, ksp, vsp):
            return paged_attention_decode(
                q, kp, vp, tbl, p, k_scale_pool=ksp, v_scale_pool=vsp,
                scale=scale)
    else:
        def kernel_fn(q, kp, vp, tbl, p, ksp, vsp):
            return flash_decode_paged(
                q, kp, vp, tbl, p, k_scale_pool=ksp, v_scale_pool=vsp,
                scale=scale, pages_per_step=pages_per_step)

    def attend_update(q, k_new, v_new, state):
        k_pool, v_pool, ks_pool, vs_pool = state
        if quant:
            k_new, k_s = _quantize_rows(k_new, cfg.kv_cache_dtype)
            v_new, v_s = _quantize_rows(v_new, cfg.kv_cache_dtype)
            ks_pool = _scale_write(ks_pool, page_ids, page, offset, k_s,
                                   pages, ident)
            vs_pool = _scale_write(vs_pool, page_ids, page, offset, v_s,
                                   pages, ident)
        k_pool = _pool_write(k_pool, page_ids, page, offset, k_new,
                             pages, ident)
        v_pool = _pool_write(v_pool, page_ids, page, offset, v_new,
                             pages, ident)
        if kernel_route is None:
            o = _paged_attend_gather(q, k_pool, v_pool, ks_pool,
                                     vs_pool, table, pos, cfg, scale)
        elif paged_sharded:
            # manual partition over tp, mirroring decode_step's linear
            # route: q heads block-shard with their kv heads, pools
            # shard on the kv_heads dim, table/pos ride replicated.
            # (PS, not the module alias P — the page size shadows it
            # in this scope.)
            from jax.sharding import PartitionSpec as PS

            spec_q, spec_pool = _tp_serving_specs(mesh, cfg)
            pos_arr = (pos if ragged
                       else jnp.asarray(pos, jnp.int32).reshape(1))
            args = [q, k_pool, v_pool, table, pos_arr]
            specs = [spec_q, spec_pool, spec_pool, PS(), PS()]
            if quant:
                args += [ks_pool, vs_pool]
                specs += [spec_pool, spec_pool]

            def local_attn(q, kp, vp, tbl, p, ksp=None, vsp=None):
                return kernel_fn(q, kp, vp, tbl,
                                 p if ragged else p[0], ksp, vsp)

            o = shard_map(
                local_attn, mesh=mesh, in_specs=tuple(specs),
                out_specs=spec_q,
                check_vma=False,  # pallas_call can't declare vma
            )(*args)
        else:
            o = kernel_fn(q, k_pool, v_pool, table, pos, ks_pool,
                          vs_pool)
        return o, (k_pool, v_pool, ks_pool, vs_pool)

    states = [
        (cache["k"][l], cache["v"][l],
         cache["k_scale"][l] if quant else None,
         cache["v_scale"][l] if quant else None)
        for l in range(cfg.n_layers)
    ]
    logits, new_states = _token_step(params, pos, tokens, cfg,
                                     states, attend_update)
    out = {
        "k": tuple(s[0] for s in new_states),
        "v": tuple(s[1] for s in new_states),
        "table": table,
    }
    if quant:
        out["k_scale"] = tuple(s[2] for s in new_states)
        out["v_scale"] = tuple(s[3] for s in new_states)
    return logits, out


def paged_extend_step(params, cache, pos, tokens, cfg: TransformerConfig):
    """RAGGED multi-token cache extension against the paged cache: row
    ``b``'s chunk ``tokens[b]`` occupies positions ``pos[b] ..
    pos[b]+c-1`` — every row at its own length, the verification
    primitive per-row-progress batched speculative decoding needs
    (:mod:`~hpc_patterns_tpu.models.speculative`). ``pos``: (B,) int32.

    The chunk K/V scatter into the pool at per-row page/offset pairs
    (the ragged write generalized from one row to ``c``); attention is
    the gather form over the table-linearized pools — a c-row query
    block against the live prefix is MXU territory, exactly
    :func:`extend_step`'s reasoning, with per-row causal masks
    ``row <= pos[b]+i``. int8 pools compose: chunk rows quantize
    per-row like :func:`paged_decode_step`'s writes, and the gather
    dequantizes the linearized view (unlike linear
    :func:`extend_step`, which stays compute-only).
    Returns (logits (B, c, vocab) f32, updated cache).

    CONTRACT (same as :func:`paged_decode_step`): every touched
    position < pages_per_seq * page_size; concrete ``pos`` is checked,
    traced ``pos`` clamps silently past capacity.
    """
    quant = _kv_quantized(cfg)
    dt = jnp.dtype(cfg.dtype)
    B, c = tokens.shape
    if jnp.ndim(pos) != 1 or jnp.shape(pos)[0] != B:
        raise ValueError(
            f"pos must be (batch,)={B} per-row positions, got "
            f"{jnp.shape(pos)}")
    table = cache["table"]
    Pg = cache["k"][0].shape[2]
    pages = table.shape[1]
    if not isinstance(pos, jax.core.Tracer):
        if np.any(np.asarray(pos) + c > pages * Pg):
            raise ValueError(
                f"chunk end {int(np.asarray(pos).max()) + c} past cache "
                f"capacity {pages * Pg} tokens")
    scale = 1.0 / (cfg.head_dim ** 0.5)
    Hkv, g, Dh = cfg.kv_heads, cfg.n_heads // cfg.kv_heads, cfg.head_dim

    positions = pos[:, None] + jnp.arange(c, dtype=jnp.int32)  # (B, c)
    x = params["embed"].astype(dt)[tokens]
    if cfg.pos_embed == "learned":
        x = x + params["pos_embed"].astype(dt)[positions]

    page = positions // Pg
    off = (positions % Pg).reshape(-1)  # (B*c,)
    pids = jnp.take_along_axis(table, page, axis=1).reshape(-1)

    def lin_view(pool):
        # table-linearized view: (B, Hkv, pages*Pg, D) — the extend
        # reads the whole live prefix once, gather-form
        return jnp.einsum("bphsd->bhpsd", pool[table]).reshape(
            B, Hkv, pages * Pg, Dh)

    def lin_scales(spool):
        # (pool, Hkv, 1, Pg) lane-major -> (B, Hkv, pages*Pg)
        return jnp.einsum("bphls->bhpls", spool[table]).reshape(
            B, Hkv, pages * Pg)

    def body(h, lp, state):
        k_pool, v_pool, ks_pool, vs_pool = state
        hn = _rmsnorm(h, lp["ln1_scale"])
        q, k_new, v_new = project_qkv(hn, lp, cfg)  # (B, c, H/Hkv, Dh)
        if cfg.pos_embed == "rope":
            q = apply_rope(q, positions, cfg)
            k_new = apply_rope(k_new, positions, cfg)
        rows_k = k_new.reshape(B * c, Hkv, Dh)
        rows_v = v_new.reshape(B * c, Hkv, Dh)
        if quant:
            rows_k, k_s = _quantize_rows(rows_k, cfg.kv_cache_dtype)
            rows_v, v_s = _quantize_rows(rows_v, cfg.kv_cache_dtype)
            ks_pool = ks_pool.at[pids, :, 0, off].set(k_s)
            vs_pool = vs_pool.at[pids, :, 0, off].set(v_s)
        k_pool = k_pool.at[pids, :, off, :].set(
            rows_k.astype(k_pool.dtype))
        v_pool = v_pool.at[pids, :, off, :].set(
            rows_v.astype(v_pool.dtype))
        if quant:
            kd = (lin_view(k_pool).astype(jnp.float32)
                  * lin_scales(ks_pool)[..., None])
            vd = (lin_view(v_pool).astype(jnp.float32)
                  * lin_scales(vs_pool)[..., None])
        else:
            kd = lin_view(k_pool).astype(jnp.float32)
            vd = lin_view(v_pool).astype(jnp.float32)
        qg = q.reshape(B, c, Hkv, g, Dh)
        s = jnp.einsum(
            "bckgd,bksd->bkgcs", qg.astype(jnp.float32), kd,
            precision=lax.Precision.HIGHEST,
        ) * scale
        row_pos = lax.broadcasted_iota(jnp.int32, s.shape, 4)
        q_pos = positions[:, None, None, :, None]  # (B,1,1,c,1)
        s = jnp.where(row_pos <= q_pos, s, -1e30)
        p = jax.nn.softmax(s, axis=-1)
        o = jnp.einsum("bkgcs,bksd->bckgd", p, vd,
                       precision=lax.Precision.HIGHEST)
        o = jnp.dot(o.reshape(B, c, cfg.d_model).astype(dt),
                    matmul_weight(lp, "wo", dt))
        h = _mlp(h + o, lp, cfg)
        return h, (k_pool, v_pool, ks_pool, vs_pool)

    states = []
    for l in range(cfg.n_layers):
        lp = jax.tree.map(lambda a: a[l], params["layers"])
        x, st = body(x, lp, (
            cache["k"][l], cache["v"][l],
            cache["k_scale"][l] if quant else None,
            cache["v_scale"][l] if quant else None,
        ))
        states.append(st)
    x = _rmsnorm(x, params["ln_f_scale"])
    logits = jnp.dot(x, matmul_weight(params, "lm_head", dt))
    out = {
        "k": tuple(s[0] for s in states),
        "v": tuple(s[1] for s in states),
        "table": table,
    }
    if quant:
        out["k_scale"] = tuple(s[2] for s in states)
        out["v_scale"] = tuple(s[3] for s in states)
    return logits.astype(jnp.float32), out


@partial(jax.jit, static_argnums=(2, 3, 4, 5, 8, 9, 10))
def _paged_generate_jit(params, prompt, cfg, new_tokens, page_size,
                        pages_per_seq, key, temperature, greedy, top_k,
                        mesh=None):
    B, T = prompt.shape
    cache = init_paged_cache(cfg, B, pages_per_seq, page_size)
    logits, cache = paged_prefill(params, prompt, cfg, cache, page_size,
                                  mesh=mesh)
    # the jit built its own default (identity) table above, so the
    # in-place DUS write path is sound
    return _generation_scan(
        lambda c, p, t: paged_decode_step(params, c, p, t, cfg,
                                          identity_layout=True,
                                          mesh=mesh),
        logits, cache, T, new_tokens, key, temperature, greedy, top_k,
    )


def paged_generate(params, prompt, cfg: TransformerConfig,
                   new_tokens: int, *, page_size: int = 512,
                   pages_per_seq: int | None = None, key=None,
                   temperature: float = 0.0, top_k: int = 0, mesh=None):
    """Continuation (B, new_tokens) int32 served from the paged cache —
    token-identical to :func:`generate` (the paged kernel reproduces
    the linear kernel's f32 math exactly; oracle-tested). The cache
    footprint is ``pages_per_seq * page_size`` tokens per sequence
    (default: just enough pages for prompt + new_tokens) instead of the
    linear cache's ``max_len`` — THE serving-capacity lever when the
    declared maximum is far above typical generation length. ``mesh``:
    tp-sharded paged serving (the two serving levers compose — see
    :func:`paged_decode_step`)."""
    if new_tokens < 1:
        raise ValueError(f"new_tokens must be >= 1, got {new_tokens}")
    B, T = prompt.shape
    need = T + new_tokens
    if need > cfg.max_seq:
        raise ValueError(
            f"prompt {T} + new {new_tokens} exceeds max_seq {cfg.max_seq}"
        )
    if pages_per_seq is None:
        pages_per_seq = -(-need // page_size)
    if pages_per_seq * page_size < need:
        raise ValueError(
            f"{pages_per_seq} pages of {page_size} < {need} tokens"
        )
    if temperature > 0.0 and key is None:
        raise ValueError("sampling (temperature > 0) needs a PRNG key")
    if key is None:
        key = jax.random.PRNGKey(0)
    with tracelib.compile_watch("decode.paged_generate",
                                _paged_generate_jit,
                                batch=B, prompt_len=T,
                                new_tokens=new_tokens,
                                page_size=page_size):
        return _paged_generate_jit(
            params, prompt, cfg, new_tokens, page_size, pages_per_seq,
            key, jnp.float32(max(temperature, 1e-6)),
            temperature <= 0.0, int(top_k), mesh,
        )
