"""Known-clean: wire codec where both directions agree. Mandatory
fields are declared in REQUIRED_WIRE_FIELDS and may be indexed
directly; every other read is absent-tolerant (``.get`` or an
``in``-guard); every written field is read and vice versa. Zero
findings expected."""

REQUIRED_WIRE_FIELDS = ("seq_id", "pos")


def bundle_to_wire(seq):
    return {
        "seq_id": seq.seq_id,
        "pos": seq.pos,
        "deadline_s": seq.deadline_s,
        "segments": [list(s) for s in seq.segments],
    }


def bundle_from_wire(wire):
    seq_id = wire["seq_id"]
    pos = wire["pos"]
    deadline_s = wire.get("deadline_s", 0.0)
    segments = wire["segments"] if "segments" in wire else []
    return seq_id, pos, deadline_s, segments
