"""Prefix-sharing KV arena (models/serving.py ``prefix_cache=True`` +
memory/prefix_cache.py): a sharing engine must be TOKEN-IDENTICAL to a
private-pages engine — greedy AND sampled — no matter where the
prompts diverge (page boundary vs mid-page), what evicted whom along
the way (preemption decrefs, never frees), or which engine finished
the row (migration bundles carry prefix refs a warm destination
resolves, or it materializes). The bitwise story behind the oracle
(rung-keyed chains, PREFIX_ALIGN row stability, the einsum-mirror tail
prefill) lives in docs/prefix_cache.md; this file pins its observable
consequences. The module runs under the donation-poison harness
(conftest) like test_serving.py — a zero-copy view of a donated pool
fails loudly here."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from hpc_patterns_tpu.memory.prefix_cache import RadixPrefixCache
from hpc_patterns_tpu.models import TransformerConfig, init_params
from hpc_patterns_tpu.models.decode import paged_generate
from hpc_patterns_tpu.models.serving import (
    ContinuousBatcher,
    tail_prefill_cache_size,
)
from hpc_patterns_tpu.serving_plane.migration import (
    bundle_from_wire,
    bundle_to_wire,
)

BASE = dict(vocab=64, d_model=32, n_heads=4, n_layers=2, d_ff=64,
            max_seq=64, dtype="float32")
BUCKETS = (16, 24, 32)


def _setup(**over):
    cfg = TransformerConfig(**{**BASE, **over})
    params = init_params(jax.random.PRNGKey(0), cfg)
    return cfg, params


def _standalone(params, cfg, prompt, max_new, **kw):
    return np.asarray(paged_generate(
        params, jnp.asarray(prompt, jnp.int32)[None, :], cfg, max_new,
        page_size=8, **kw))[0]


def _engine(params, cfg, share=True, **over):
    kw = dict(slots=2, pool_pages=12, pages_per_seq=4, page_size=8,
              chunk=2, prompt_buckets=BUCKETS, prefix_cache=share)
    kw.update(over)
    return ContinuousBatcher(params, cfg, **kw)


def _template_requests(cfg, template, n, seed=0, tails=(3, 5, 8)):
    rng = np.random.RandomState(seed)
    reqs = []
    for _ in range(n):
        tail = rng.randint(0, cfg.vocab,
                           size=int(rng.choice(tails))).astype(np.int32)
        reqs.append((np.concatenate([template, tail]),
                     int(rng.choice([3, 5]))))
    return reqs


class TestRadixPrefixCache:
    """Host-only unit behavior of the index itself."""

    def test_match_insert_roundtrip_and_rung_scoping(self):
        c = RadixPrefixCache(4)
        toks = np.arange(12, dtype=np.int32)
        assert c.insert(toks, 16, [7, 3, 9]) == [7, 3, 9]
        assert c.match(toks, 16) == [7, 3, 9]
        # a shorter shared prefix matches its chain prefix
        assert c.match(np.concatenate([toks[:8], toks[:4]]), 16) == [7, 3]
        # rung-keyed: the SAME tokens at another rung are a miss
        assert c.match(toks, 32) == []
        # max_pages caps the walk
        assert c.match(toks, 16, max_pages=1) == [7]

    def test_insert_keeps_first_writer(self):
        c = RadixPrefixCache(4)
        toks = np.arange(8, dtype=np.int32)
        assert c.insert(toks, 16, [1, 2]) == [1, 2]
        # a duplicate insert (same-pass double admission) returns no
        # new pages: the second writer's private pages stay private
        assert c.insert(toks, 16, [5, 6]) == []
        assert c.match(toks, 16) == [1, 2]

    def test_evict_lru_leaves_only(self):
        c = RadixPrefixCache(4)
        a = np.arange(12, dtype=np.int32)
        b = np.concatenate([a[:4], np.arange(50, 54, dtype=np.int32)])
        c.insert(a, 16, [1, 2, 3])
        c.insert(b, 16, [1, 9])
        c.match(b, 16)  # touch b's chain; a's tip (3) is now LRU
        freed = c.evict(1, lambda p: True)
        assert freed == [3]
        # interior node 1 has children — never offered while they live
        freed = c.evict(10, lambda p: True)
        assert set(freed) == {2, 9, 1}
        assert len(c) == 0

    def test_evict_respects_refcounts(self):
        c = RadixPrefixCache(4)
        c.insert(np.arange(8, dtype=np.int32), 16, [1, 2])
        # page 2 is "mapped by a row" (refcount 2): never evicted
        freed = c.evict(5, lambda p: p != 2)
        assert freed == []
        assert c.has_page(2)

    def test_release_pages_deepest_first_stops_at_children(self):
        c = RadixPrefixCache(4)
        a = np.arange(12, dtype=np.int32)
        b = np.concatenate([a[:8], np.arange(60, 64, dtype=np.int32)])
        c.insert(a, 16, [1, 2, 3])
        c.insert(b, 16, [1, 2, 7])
        # releasing a's pages drops leaf 3; 1 and 2 anchor b's chain
        assert c.release_pages([1, 2, 3]) == [3]
        assert c.match(b, 16) == [1, 2, 7]

    def test_clear_returns_everything(self):
        c = RadixPrefixCache(4)
        c.insert(np.arange(12, dtype=np.int32), 16, [4, 5, 6])
        assert c.clear() == [4, 5, 6]
        assert c.match(np.arange(12, dtype=np.int32), 16) == []


class TestSharingOracle:
    """The tentpole oracle: sharing is invisible in the tokens."""

    @pytest.mark.parametrize("temp", [0.0, 0.8])
    def test_shared_equals_private_and_standalone(self, temp):
        cfg, params = _setup()
        rng = np.random.RandomState(1)
        template = rng.randint(0, cfg.vocab, size=16).astype(np.int32)
        reqs = _template_requests(cfg, template, 6, seed=2)
        reqs.append((template.copy(), 4))  # full-identical prompt
        skw = dict(temperature=temp, top_k=0 if temp == 0 else 8)
        before = tail_prefill_cache_size()
        priv = _engine(params, cfg, share=False, **skw)
        ids_p = [priv.submit(p, b) for p, b in reqs]
        got_p = priv.run()
        shr = _engine(params, cfg, **skw)
        ids_s = [shr.submit(p, b) for p, b in reqs]
        got_s = shr.run()
        for i, (p, b) in enumerate(reqs):
            gen_kw = {} if temp == 0 else dict(
                temperature=temp, top_k=8,
                key=shr.request_key(ids_s[i]))
            want = _standalone(params, cfg, p, b, **gen_kw)
            np.testing.assert_array_equal(got_p[ids_p[i]], want,
                                          err_msg=f"private {i}")
            np.testing.assert_array_equal(got_s[ids_s[i]], want,
                                          err_msg=f"shared {i}")
        assert shr._prefix.hits > 0
        assert shr.prefill_skip_frac > 0.3
        # compile bound: one tail variant per (matched pages, rung)
        assert (tail_prefill_cache_size() - before
                <= len(BUCKETS) * shr.pages_per_seq)
        # drained arena: rows released, the index still holds chains —
        # clearing it returns every page
        shr.release_prefix_cache()
        assert sorted(shr.free_pages) == list(range(12))
        assert sorted(priv.free_pages) == list(range(12))

    def test_divergence_at_page_boundary_vs_mid_page(self):
        cfg, params = _setup()
        rng = np.random.RandomState(3)
        template = rng.randint(0, cfg.vocab, size=16).astype(np.int32)
        events = []
        eng = _engine(params, cfg,
                      emit=lambda **kw: events.append(kw))
        # all three prompts are 21 tokens -> the SAME rung (24):
        # sharing is rung-keyed, so the seed must land where the
        # readers will look
        seeder = np.concatenate(
            [template, rng.randint(0, cfg.vocab, size=5).astype(np.int32)])
        seed = eng.submit(seeder, 3)  # seeds template pages 0..1
        eng.run()
        boundary = np.concatenate(  # diverges exactly at token 16
            [template, rng.randint(0, cfg.vocab, size=5).astype(np.int32)])
        midpage = np.concatenate(   # diverges at token 12, mid-page
            [template[:12],
             rng.randint(0, cfg.vocab, size=9).astype(np.int32)])
        b = eng.submit(boundary, 4)
        m = eng.submit(midpage, 4)
        got = eng.run()
        admits = {e["seq_id"]: e for e in events
                  if e["kind"] == "serve_admit"}
        # boundary divergence: both template pages map shared
        assert admits[b]["matched_tokens"] == 16
        # mid-page divergence: only the full page BEFORE the split —
        # the boundary page is private from admission (COW-at-admission)
        assert admits[m]["matched_tokens"] == 8
        for sid, prompt in ((seed, seeder), (b, boundary),
                            (m, midpage)):
            np.testing.assert_array_equal(
                got[sid],
                _standalone(params, cfg, prompt,
                            4 if sid != seed else 3))

    def test_match_is_rung_keyed(self):
        # the SAME 16-token template through prompts on two different
        # rungs must not share: prefix K/V bytes are rung-stamped
        cfg, params = _setup()
        rng = np.random.RandomState(4)
        template = rng.randint(0, cfg.vocab, size=16).astype(np.int32)
        events = []
        eng = _engine(params, cfg,
                      emit=lambda **kw: events.append(kw))
        a = eng.submit(  # 21 tokens -> rung 24
            np.concatenate([template,
                            rng.randint(0, cfg.vocab, size=5)
                            .astype(np.int32)]), 3)
        eng.run()
        b = eng.submit(  # 29 tokens -> rung 32: no rung-24 chain match
            np.concatenate([template,
                            rng.randint(0, cfg.vocab, size=13)
                            .astype(np.int32)]), 3)
        got = eng.run()
        admits = {e["seq_id"]: e for e in events
                  if e["kind"] == "serve_admit"}
        assert admits[b]["matched_tokens"] == 0
        c = eng.submit(  # 23 tokens -> rung 24 again: shares
            np.concatenate([template,
                            rng.randint(0, cfg.vocab, size=7)
                            .astype(np.int32)]), 3)
        got2 = eng.run()
        admits = {e["seq_id"]: e for e in events
                  if e["kind"] == "serve_admit"}
        assert admits[c]["matched_tokens"] == 16
        assert len(got[b]) == 3 and len(got2[c]) == 3

    def test_sharing_admits_where_private_pages_cannot(self):
        # THE capacity claim in one shape: a pool too small for two
        # private working sets serves both requests when the second
        # maps the first's pages
        cfg, params = _setup()
        rng = np.random.RandomState(5)
        template = rng.randint(0, cfg.vocab, size=16).astype(np.int32)
        pA = np.concatenate(
            [template, rng.randint(0, cfg.vocab, size=3).astype(np.int32)])
        pB = np.concatenate(
            [template, rng.randint(0, cfg.vocab, size=3).astype(np.int32)])
        # each request needs 3 pages privately (19 + 4 <= 24 = 3 pages
        # on the rung-24 pad); pool of 4: private engines can never
        # hold both, sharing maps 2 template pages so B needs only 1
        # private page beside A's 3 (chunk=1 keeps A mid-flight — 2 of
        # 4 tokens — through B's admission round)
        kw = dict(slots=2, pool_pages=4, pages_per_seq=3, page_size=8,
                  chunk=1, prompt_buckets=BUCKETS)
        shr = ContinuousBatcher(params, cfg, prefix_cache=True, **kw)
        a = shr.submit(pA, 4)
        shr.run(max_rounds=1)          # A resident, holding 3 pages
        b = shr.submit(pB, 4)
        shr.run(max_rounds=1)
        assert shr.active_count == 2, (
            "B should have admitted beside A through the shared pages")
        got = shr.run()
        np.testing.assert_array_equal(got[a],
                                      _standalone(params, cfg, pA, 4))
        np.testing.assert_array_equal(got[b],
                                      _standalone(params, cfg, pB, 4))

    def test_reclaim_frees_cache_only_pages_for_admission(self):
        # a drained engine whose index holds every page must still
        # admit fresh unrelated work: LRU cache-only pages reclaim
        cfg, params = _setup()
        rng = np.random.RandomState(6)
        eng = _engine(params, cfg, pool_pages=6, pages_per_seq=3)
        for i in range(3):  # fill the index with disjoint chains
            p = rng.randint(0, cfg.vocab, size=16).astype(np.int32)
            eng.submit(p, 3)
            eng.run()
        assert len(eng.free_pages) < 6  # the index holds pages
        fresh = rng.randint(0, cfg.vocab, size=20).astype(np.int32)
        sid = eng.submit(fresh, 4)      # needs 3 pages
        got = eng.run()
        np.testing.assert_array_equal(
            got[sid], _standalone(params, cfg, fresh, 4))

    def test_constructor_refuses_unshareable_configs(self):
        cfg, params = _setup()
        kw = dict(slots=1, pool_pages=4, pages_per_seq=4, page_size=8,
                  chunk=2)
        with pytest.raises(ValueError, match="RUNG-KEYED"):
            ContinuousBatcher(params, cfg, prefix_cache=True, **kw)
        with pytest.raises(ValueError, match="aligned"):
            ContinuousBatcher(params, cfg, prefix_cache=True,
                              prompt_buckets=(12, 20), **kw)
        cfg8, params8 = _setup(kv_cache_dtype="int8")
        with pytest.raises(ValueError, match="int8"):
            ContinuousBatcher(params8, cfg8, prefix_cache=True,
                              prompt_buckets=BUCKETS, **kw)


class TestCowComposition:
    """COW under preemption, migration, and residency."""

    @pytest.mark.parametrize("temp", [0.0, 0.8])
    def test_preempt_resume_of_sharing_row(self, temp):
        # the victim's prompt pages are in the index (decref on evict,
        # NOT freed — the chain survives); the resume re-enters through
        # the ordinary admission and RE-MATCHES the chain at its rung
        cfg, params = _setup()
        rng = np.random.RandomState(7)
        template = rng.randint(0, cfg.vocab, size=8).astype(np.int32)
        pV = np.concatenate(
            [template, rng.randint(0, cfg.vocab, size=1).astype(np.int32)])
        events = []
        skw = dict(temperature=temp, top_k=0 if temp == 0 else 8)
        eng = ContinuousBatcher(
            params, cfg, slots=2, pool_pages=4, pages_per_seq=4,
            page_size=8, chunk=2, preempt=True, prefix_cache=True,
            prompt_buckets=BUCKETS,
            emit=lambda **kw: events.append(kw), **skw)
        v = eng.submit(pV, 18, priority=1)  # 9 + 18 -> all 4 pages
        eng.run(max_rounds=3)
        h = eng.submit(template.copy(), 4, priority=0)  # must evict V
        got = eng.run()
        pre = [e for e in events if e["kind"] == "serve_preempt"]
        assert [e["seq_id"] for e in pre] == [v]
        gen_kw = ({} if temp == 0 else
                  {"temperature": temp, "top_k": 8})
        np.testing.assert_array_equal(
            got[v], _standalone(
                params, cfg, pV, 18,
                **({**gen_kw, "key": eng.request_key(v)} if temp
                   else {})))
        np.testing.assert_array_equal(
            got[h], _standalone(
                params, cfg, template, 4,
                **({**gen_kw, "key": eng.request_key(h)} if temp
                   else {})))
        # the resumed admission re-matched the surviving chain
        resumed = [e for e in events
                   if e["kind"] == "serve_admit" and e["resumed"]]
        assert resumed and resumed[0]["matched_tokens"] >= 8
        eng.release_prefix_cache()
        assert sorted(eng.free_pages) == list(range(4))

    @pytest.mark.parametrize("temp", [0.0, 0.8])
    def test_migration_materialized_vs_resolved(self, temp):
        # one exported bundle, two destinations: a COLD cache installs
        # every payload page; a WARM cache resolves the prefix span to
        # its own shared pages — byte-exact either way
        cfg, params = _setup()
        rng = np.random.RandomState(8)
        template = rng.randint(0, cfg.vocab, size=16).astype(np.int32)
        prompt = np.concatenate(
            [template, rng.randint(0, cfg.vocab, size=5).astype(np.int32)])
        skw = dict(temperature=temp, top_k=0 if temp == 0 else 8,
                   seed=0)
        kw = dict(slots=2, pool_pages=8, pages_per_seq=4, page_size=8,
                  chunk=2, prompt_buckets=BUCKETS, prefix_cache=True,
                  **skw)
        src = ContinuousBatcher(params, cfg, **kw)
        sid = src.submit(prompt, 6, seq_id=7)  # distinct from the
        src.service_round(decode=False)        # warm engine's own ids
        bundle = src.export_migration(src.exportable_slots()[0])
        assert bundle.rung == 24 and bundle.prefix_len == 16
        wire = bundle_from_wire(bundle_to_wire(bundle))
        assert (wire.rung, wire.prefix_len) == (24, 16)
        want = _standalone(
            params, cfg, prompt, 6,
            **({} if temp == 0 else dict(temperature=temp, top_k=8,
                                         key=src.request_key(sid))))

        cold = ContinuousBatcher(params, cfg, **kw)
        s_cold = cold.install_migration(wire)
        assert cold._slots[s_cold].shared_pages == 0  # materialized
        np.testing.assert_array_equal(cold.run()[sid], want)

        warm = ContinuousBatcher(params, cfg, **kw)
        w = warm.submit(np.concatenate(  # seeds the rung-24 chain
            [template, rng.randint(0, cfg.vocab, size=7)
             .astype(np.int32)]), 3)
        warm.run()
        s_warm = warm.install_migration(bundle)
        assert warm._slots[s_warm].shared_pages == 2  # refs resolved
        np.testing.assert_array_equal(warm.run()[sid], want)
        assert len(warm.run()[sid]) == len(want) and w in warm.finished

    def test_migration_seeds_the_destination_index(self):
        # the PR 11 remainder, pinned: a migration into a COLD
        # destination doesn't just materialize — install_migration
        # PUBLISHES the migrated-in row's prefix span into the
        # destination's radix index, so the migration WARMS the new
        # engine's sharing arena (the elastic plane's scale-up/drain
        # path: a freshly spun-up replica starts sharing immediately)
        cfg, params = _setup()
        rng = np.random.RandomState(8)
        template = rng.randint(0, cfg.vocab, size=16).astype(np.int32)
        prompt = np.concatenate(
            [template,
             rng.randint(0, cfg.vocab, size=5).astype(np.int32)])
        kw = dict(slots=2, pool_pages=12, pages_per_seq=4, page_size=8,
                  chunk=2, prompt_buckets=BUCKETS, prefix_cache=True)
        src = ContinuousBatcher(params, cfg, **kw)
        sid = src.submit(prompt, 4, seq_id=7)
        src.service_round(decode=False)
        bundle = src.export_migration(src.exportable_slots()[0])

        cold = ContinuousBatcher(params, cfg, **kw)
        s_cold = cold.install_migration(bundle)
        assert cold._slots[s_cold].shared_pages == 0  # materialized
        assert cold._prefix.hits == 0
        # a subsequent same-template admission on the destination
        # MATCHES the seeded chain: shared pages mapped, prefill
        # skipped, tokens still standalone-exact
        p2 = np.concatenate(
            [template,
             rng.randint(0, cfg.vocab, size=7).astype(np.int32)])
        w = cold.submit(p2, 3)
        got = cold.run()
        assert cold._prefix.hits >= 1
        assert cold._prefill_skip_tokens >= 16
        np.testing.assert_array_equal(
            got[w], _standalone(params, cfg, p2, 3))
        np.testing.assert_array_equal(
            got[sid], _standalone(params, cfg, prompt, 4))

    def test_pin_while_shared_blocks_residency_paging(self):
        # refcount >= 2 (net of the index's own reference): the row is
        # PINNED — the manager must never page it to host while the
        # second reader is resident; a lone reader is swappable again
        from hpc_patterns_tpu.memory import (
            ColdAfterNPolicy,
            ResidencyManager,
        )

        cfg, params = _setup()
        rng = np.random.RandomState(9)
        template = rng.randint(0, cfg.vocab, size=16).astype(np.int32)
        pA = np.concatenate(
            [template, rng.randint(0, cfg.vocab, size=3).astype(np.int32)])
        pB = np.concatenate(
            [template, rng.randint(0, cfg.vocab, size=5).astype(np.int32)])
        mgr = ResidencyManager(host_blocks=16,
                               policy=ColdAfterNPolicy(1))
        eng = ContinuousBatcher(
            params, cfg, slots=2, pool_pages=10, pages_per_seq=4,
            page_size=8, chunk=2, prompt_buckets=BUCKETS,
            prefix_cache=True, residency=mgr)
        a = eng.submit(pA, 8)
        b = eng.submit(pB, 8)
        eng.run(max_rounds=2)  # both resident, sharing the template
        slots = {s.seq_id: i for i, s in enumerate(eng._slots)
                 if s.active}
        assert not eng._row_swappable(slots[a])
        assert not eng._row_swappable(slots[b])
        assert all(g.pinned for g in mgr.groups("hbm"))
        got = eng.run()
        np.testing.assert_array_equal(got[a],
                                      _standalone(params, cfg, pA, 8))
        np.testing.assert_array_equal(got[b],
                                      _standalone(params, cfg, pB, 8))
        # a lone reader (index ref only beside its own) is swappable
        c = eng.submit(np.concatenate(
            [template, rng.randint(0, cfg.vocab, size=4)
             .astype(np.int32)]), 8)
        eng.run(max_rounds=1)
        sc = next(i for i, s in enumerate(eng._slots)
                  if s.active and s.seq_id == c)
        assert eng._row_swappable(sc)
        eng.run()

    def test_poison_covers_tail_prefill(self):
        # the donation-poison harness (active for this whole module,
        # conftest) must wrap the new page-install jit: an aliased
        # shared page would corrupt every reader at once
        from hpc_patterns_tpu.analysis import runtime
        from hpc_patterns_tpu.models import serving

        assert runtime.SERVING_POISON_TARGETS["_tail_prefill_one"] \
            == (3,)
        assert getattr(serving._tail_prefill_one, "__wrapped__",
                       None) is not None
