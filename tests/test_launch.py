"""Multi-process launches: the mpirun -np analog end to end.

The reference's distributed tests are `mpirun -np 4 ./app` CTest cases
(src/CMakeLists.txt:39-50). Here apps/launch.py spawns real OS
processes joined via jax.distributed over a local coordinator, CPU
devices standing in for chips — cross-process collectives,
cross-process MAX timing, and per-rank validation all run for real
(SURVEY.md §4's hardware-free-testing gap, closed at the process
level too)."""

import sys

import pytest

from hpc_patterns_tpu.apps import launch

pytestmark = pytest.mark.slow  # each case boots 2 jax processes


def _launch(app_args, np_=2, devices=2, slices=0):
    return launch.main([
        "-np", str(np_), "--cpu-devices-per-proc", str(devices),
        *(["--slices", str(slices)] if slices else []), "--",
        sys.executable, "-m", *app_args,
    ])


class TestLaunch:
    def test_allreduce_ring_4_ranks_2_processes(self, capsys):
        code = _launch(["hpc_patterns_tpu.apps.allreduce_app", "-p", "8",
                        "--repetitions", "2", "--warmup", "1"])
        out = capsys.readouterr().out
        assert code == 0, out
        # every global rank validated, split across the two processes
        for r in range(4):
            assert f"Passed {r}" in out
        assert "world=4" in out

    def test_pingpong_across_processes(self, capsys):
        code = _launch(["hpc_patterns_tpu.apps.pingpong_app", "-p", "6",
                        "--min-p", "6", "--repetitions", "2",
                        "--warmup", "1"])
        out = capsys.readouterr().out
        assert code == 0, out
        assert "ok" in out

    def test_train_dp_across_processes(self, capsys):
        # the flagship train step as true multi-process SPMD: dp=4 over
        # 2 OS processes, gradient all-reduce crossing the process
        # boundary
        code = _launch(["hpc_patterns_tpu.apps.train_app", "--dp", "4",
                        "--steps", "2", "--batch", "8", "--seq", "32",
                        "--d-model", "32", "--n-layers", "1",
                        "--vocab", "128"])
        out = capsys.readouterr().out
        assert code == 0, out

    def test_train_pp_stages_in_separate_processes(self, capsys):
        # 1F1B pipeline with each stage living in a different OS process
        code = _launch(["hpc_patterns_tpu.apps.train_app", "--pp", "2",
                        "--steps", "2", "--batch", "4",
                        "--microbatches", "2", "--seq", "32",
                        "--d-model", "32", "--n-layers", "2",
                        "--vocab", "128"], devices=1)
        out = capsys.readouterr().out
        assert code == 0, out

    def test_train_dcn_dp_slices_across_processes(self, capsys):
        # the multi-slice hybrid-mesh path with REAL process boundaries:
        # --slices 2 makes each OS process one "slice" (the production
        # HPCPAT_SLICE_GROUPING protocol, not a monkeypatch), so the
        # --dcn-dp gradient psum is a genuine DCN-analog collective
        # crossing processes while the tp collectives stay
        # slice-internal (each process's own 4 devices)
        code = _launch(["hpc_patterns_tpu.apps.train_app", "--dcn-dp",
                        "--dp", "-1", "--tp", "2", "--steps", "2",
                        "--batch", "4", "--seq", "32",
                        "--d-model", "32", "--n-layers", "1",
                        "--vocab", "128"], devices=4, slices=2)
        out = capsys.readouterr().out
        assert code == 0, out
        assert "SUCCESS" in out

    def test_train_pp_dcn_dp_slices_across_processes(self, capsys):
        # pp x dcn-dp: the 1F1B stage ppermutes stay slice-internal
        # (each process's own devices) while the once-per-step dp
        # gradient pmean crosses the OS process boundary
        code = _launch(["hpc_patterns_tpu.apps.train_app", "--dcn-dp",
                        "--dp", "-1", "--pp", "2", "--steps", "2",
                        "--batch", "4", "--microbatches", "2",
                        "--seq", "32", "--d-model", "32",
                        "--n-layers", "2", "--vocab", "128"],
                       devices=4, slices=2)
        out = capsys.readouterr().out
        assert code == 0, out
        assert "SUCCESS" in out and "dcn-dp=2" in out

    def test_train_pp_tp_across_processes(self, capsys):
        # Megatron tp inside pipeline stages with the mesh spanning two
        # OS processes: the per-layer tp psums (f/g) and the sharded
        # loss head's reductions run as true cross-process collectives
        code = _launch(["hpc_patterns_tpu.apps.train_app", "--pp", "2",
                        "--tp", "2", "--steps", "2", "--batch", "4",
                        "--microbatches", "2", "--seq", "32",
                        "--d-model", "32", "--n-heads", "4",
                        "--n-layers", "2", "--vocab", "128"])
        out = capsys.readouterr().out
        assert code == 0, out
        assert "SUCCESS" in out and "tp=2" in out

    def test_train_sp_ring_attention_across_processes(self, capsys):
        # ring attention with the sp axis spanning both OS processes:
        # the per-step K/V ppermute crosses the process boundary
        code = _launch(["hpc_patterns_tpu.apps.train_app", "--sp", "4",
                        "--attention", "ring_flash", "--steps", "2",
                        "--batch", "2", "--seq", "32",
                        "--d-model", "32", "--n-layers", "1",
                        "--vocab", "128"])
        out = capsys.readouterr().out
        assert code == 0, out

    def test_failure_propagates(self, capsys):
        # a child that exits nonzero must fail the launch (ctest contract)
        code = launch.main([
            "-np", "2", "--",
            sys.executable, "-c", "import sys; sys.exit(3)",
        ])
        out = capsys.readouterr().out
        assert code == 1
        assert "FAILURE" in out

    def test_no_command_is_an_error(self, capsys):
        assert launch.main(["-np", "2"]) == 2
