"""Tiered-memory residency: policy-driven HBM <-> host paging.

The L2 allocator axis (SURVEY.md §2's ``-H/-D/-S`` memory kinds) grown
into a subsystem: one place that knows WHICH memory kinds a backend
really supports (``kinds.py`` — the probe/sharding helpers every other
module used to re-derive), and one manager that owns WHERE each block
of serving KV / training optimizer state lives right now
(``residency.py`` — per-block tier, pin state, last-touch round,
pluggable eviction policies, and the overlapped prefetch/evict
transfer pipeline measured through the flight recorder) — plus the
radix prefix index that lets the serving arena SHARE pages across
requests with common prompt prefixes (``prefix_cache.py``, round 12:
page-aligned rung-keyed nodes, longest-prefix match at admission,
refcounted page ownership staying with the arena).

Consumers:

- ``models/serving.py``: ``EngineCore(residency=...)`` treats the HBM
  page arena as a CACHE over a larger host-resident pool — admission
  consults the manager instead of failing at ``free_pages == 0``, cold
  rows page out to the host tier at chunk boundaries, and swapped rows
  prefetch back in with the pull dispatched BEFORE the decode chunk so
  the transfer hides under it (docs/memory.md);
- ``models/train.py``: ``make_train_step(..., residency=...)`` streams
  a host-resident optimizer state through the manager — the pull
  dispatches before the gradient-accumulation phase and hides under
  it, replacing the all-or-nothing in-jit move;
- ``concurrency/commands.py`` / ``apps/common.py``: delegate their
  memory-kind probes here (one probe, one answer per process).
"""

from hpc_patterns_tpu.memory.prefix_cache import RadixPrefixCache
from hpc_patterns_tpu.memory.kinds import (
    kind_sharding,
    memory_kind_placement_works,
    memory_kind_shardings,
    memory_kind_transfers_work,
    move_to_kind,
    supports_memory_kind,
)
from hpc_patterns_tpu.memory.residency import (
    BlockState,
    ColdAfterNPolicy,
    EvictionPolicy,
    LRUPolicy,
    PriorityAwarePolicy,
    ResidencyManager,
)

__all__ = [
    "BlockState",
    "ColdAfterNPolicy",
    "EvictionPolicy",
    "LRUPolicy",
    "PriorityAwarePolicy",
    "RadixPrefixCache",
    "ResidencyManager",
    "kind_sharding",
    "memory_kind_placement_works",
    "memory_kind_shardings",
    "memory_kind_transfers_work",
    "move_to_kind",
    "supports_memory_kind",
]
