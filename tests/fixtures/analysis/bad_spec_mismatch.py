"""Known-bad: PartitionSpec literals inconsistent with the module.

Three shapes: an axis name the module's own mesh never declared (a
typo jax only rejects when the spec finally meets the mesh — often on
the chip); one axis named twice in a single spec (jax rejects it at
run time); and a donated jit arg whose in-sharding matches no
out-sharding (XLA cannot alias a resharded buffer: the input still
dies, the memory saving silently doesn't happen)."""

from functools import partial

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


def build(devs):
    mesh = Mesh(np.array(devs).reshape(2, 4), ("dp", "tp"))
    batch = NamedSharding(mesh, P("dp", None))
    typo = NamedSharding(mesh, P("pp", None))  # EXPECT: spec-mismatch
    doubled = NamedSharding(mesh, P("dp", "dp"))  # EXPECT: spec-mismatch
    return batch, typo, doubled


@partial(jax.jit, donate_argnums=(0,),
         in_shardings=(P("dp", None),),  # EXPECT: spec-mismatch
         out_shardings=(P("tp", None),))
def resharding_donation(x):
    return x * 2
