"""Known-bad: the PR 8 chip-only bug shapes, minimized. Each kernel
here passed interpret mode (DMAs serialize, semaphores are inert) and
would deadlock, race, or corrupt on chip — the exact class pallaslint
exists to catch at review time. ``drain_double_wait`` and
``gather_into_rs_recv`` are line-for-line minimizations of the two
hand-found fused-ring bugs; the collective-id and dtype kernels pin
the other two review findings."""

import functools

import jax
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu


def _remote(src, dst, send, recv, dev):
    return pltpu.make_async_remote_copy(
        src_ref=src, dst_ref=dst, send_sem=send, recv_sem=recv,
        device_id=dev, device_id_type=pltpu.DeviceIdType.LOGICAL)


def drain_double_wait(x, axis, size, cn):
    """The PR 8 drain bug: the slot-reuse waits inside the ring loop
    already consumed dmas[0..size-3]'s send semaphores; the exit drain
    re-waits every one — at size >= 3 the second wait never returns on
    chip (one signal per DMA)."""

    def kernel(x_ref, o_ref, rs_recv, sendbuf, send_sem, recv_sem):
        me = lax.axis_index(axis)
        dst = lax.rem(me + 1, size)
        sendbuf[0] = x_ref[:, pl.ds(0, cn)]
        dmas = []
        d = _remote(sendbuf.at[0], rs_recv.at[0], send_sem.at[0],
                    recv_sem.at[0], dst)
        d.start()
        dmas.append(d)
        for s in range(1, size):
            dmas[s - 1].wait_recv()
            slot = s % 2
            if s >= 2:
                dmas[s - 2].wait_send()
            sendbuf[slot] = x_ref[:, pl.ds(s * cn, cn)] + rs_recv[s - 1]
            if s < size - 1:
                d = _remote(sendbuf.at[slot], rs_recv.at[s],
                            send_sem.at[slot], recv_sem.at[s], dst)
                d.start()
                dmas.append(d)
        o_ref[...] = sendbuf[(size - 1) % 2]
        for d in dmas:
            d.wait_send()  # EXPECT: dma-sem-balance

    return pl.pallas_call(kernel, out_shape=x)(x)


def undrained_send(x, axis, size):
    """A started remote DMA whose send semaphore is never waited: the
    copy outlives the kernel's scratch — racing its teardown."""

    def kernel(x_ref, o_ref, buf, send_sem, recv_sem):
        me = lax.axis_index(axis)
        d = _remote(x_ref, buf.at[0], send_sem.at[0], recv_sem.at[0],
                    lax.rem(me + 1, size))
        d.start()  # EXPECT: dma-sem-balance
        d.wait_recv()
        o_ref[...] = buf[0]

    return pl.pallas_call(kernel, out_shape=x)(x)


def gather_into_rs_recv(x, axis, size):
    """The PR 8 gather-slot bug: the gather phase lands its DMAs in
    the reduce-scatter recv slots. Nothing orders my phase-1
    completion after the neighbor's phase-1 READ of that slot — the
    gather write can clobber bytes a slower neighbor is still
    consuming. Dedicated per-phase recv buffers are the discipline."""

    def kernel(x_ref, o_ref, rs_recv, sendbuf, rs_send, rs_sem,
               ag_send, ag_sem):
        me = lax.axis_index(axis)
        dst = lax.rem(me + 1, size)
        d = _remote(sendbuf.at[0], rs_recv.at[0], rs_send.at[0],
                    rs_sem.at[0], dst)
        d.start()
        d.wait()
        g = _remote(sendbuf.at[0], rs_recv.at[1], ag_send.at[0],
                    ag_sem.at[0], dst)
        g.start()  # EXPECT: dma-slot-reuse
        g.wait()

    return pl.pallas_call(kernel, out_shape=x)(x)


def send_slot_rewritten(x, axis, size):
    """Slot reuse without the send wait: iteration s rewrites the
    alternating send buffer while the DMA issued two steps earlier may
    still be reading it — the copy can ship the NEW bytes."""

    def kernel(x_ref, o_ref, recvb, sendbuf, send_sem, recv_sem):
        me = lax.axis_index(axis)
        dst = lax.rem(me + 1, size)
        dmas = []
        for s in range(size - 1):
            slot = s % 2
            sendbuf[slot] = x_ref[...] * s  # EXPECT: dma-slot-reuse
            d = _remote(sendbuf.at[slot], recvb.at[s],
                        send_sem.at[slot], recv_sem.at[s], dst)
            d.start()
            dmas.append(d)
        for s in range(size - 1):
            dmas[s].wait_recv()
        for d in dmas:
            d.wait_send()

    return pl.pallas_call(kernel, out_shape=x)(x)


def _double_kernel(x_ref, o_ref):
    o_ref[...] = jnp_dot_like(x_ref)


def jnp_dot_like(x_ref):
    return x_ref[...]


def shared_collective_id(x, w):
    """The PR 8 shared-id bug: two kernels that can run concurrently
    in one traced region, hand-numbered onto the SAME collective_id —
    they share barrier/DMA state on chip and hang or corrupt; the
    registry (ops.tiling.collective_id) makes this unrepresentable."""
    a = pl.pallas_call(
        _double_kernel,
        out_shape=x,
        compiler_params=pltpu.CompilerParams(
            has_side_effects=True,
            collective_id=1),  # EXPECT: collective-id-collision
    )(x)
    b = pl.pallas_call(
        _double_kernel,
        out_shape=w,
        compiler_params=pltpu.CompilerParams(
            has_side_effects=True,
            collective_id=1),  # EXPECT: collective-id-collision
    )(w)
    return a, b


def _widened_store_kernel(x_ref, w_ref, o_ref):
    # the PR 8 dtype hole: an f32-widened matmul landing in the output
    # ref with no explicit narrowing cast — interpret inserts it,
    # Mosaic need not
    o_ref[...] = jax.numpy.dot(  # EXPECT: kernel-dtype-cast
        x_ref[...], w_ref[...],
        preferred_element_type=jax.numpy.float32)


def widened_store(x, w):
    return pl.pallas_call(
        functools.partial(_widened_store_kernel),
        out_shape=x,
    )(x, w)
