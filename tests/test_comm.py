"""Tests for the communication backend (C5 parity + §2.3).

Every test runs 8-way SPMD on the virtual CPU mesh (conftest), closing
the reference's hardware-only testing gap (SURVEY.md §4). Oracles are the
reference's: allreduce of rank-valued buffers == size(size-1)/2
(allreduce-mpi-sycl.cpp:192-204), elementwise, every rank.
"""

import jax

from hpc_patterns_tpu.topology import shard_map
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from hpc_patterns_tpu.comm import Communicator, collectives, ring
from hpc_patterns_tpu.harness import correctness_verdict

WORLD = 8
N = 64


@pytest.fixture(scope="module")
def comm():
    from hpc_patterns_tpu import topology

    return Communicator(topology.make_mesh({"x": WORLD}), "x")


def rows(dtype=np.float32):
    """Rank-valued buffers: row r filled with r (the miniapp's Initialize)."""
    return np.repeat(np.arange(WORLD, dtype=dtype)[:, None], N, axis=1)


ORACLE = WORLD * (WORLD - 1) / 2  # 28


@pytest.mark.parametrize("algorithm", ["collective", "ring", "ring_chunked"])
@pytest.mark.parametrize("dtype", ["float32", "int32", "bfloat16"])
def test_allreduce_all_algorithms_match_oracle(comm, algorithm, dtype):
    x = comm.shard(rows(np.dtype(dtype) if dtype != "bfloat16" else jnp.bfloat16))
    out = np.asarray(comm.allreduce(x, algorithm))
    assert out.shape == (WORLD, N)
    # every rank (row) must hold the full sum — MPI_Allreduce semantics
    v = correctness_verdict(out, ORACLE, dtype=dtype)
    assert v.success, v.messages


def test_allreduce_algorithms_agree_on_random_data(comm):
    x = comm.shard(np.random.default_rng(0).normal(size=(WORLD, N)).astype(np.float32))
    ref = np.asarray(comm.allreduce(x, "collective"))
    for alg in ["ring", "ring_chunked"]:
        # rings reduce in a different association order than XLA's
        # all-reduce; only bitwise-order-independent math would match exactly
        np.testing.assert_allclose(
            np.asarray(comm.allreduce(x, alg)), ref, rtol=1e-5, atol=1e-6
        )


def test_ring_chunked_requires_divisible_chunks(comm):
    x = comm.shard(np.ones((WORLD, WORLD + 1), np.float32))
    with pytest.raises(ValueError, match="not divisible"):
        comm.allreduce(x, "ring_chunked")


def test_rank_filled_and_oracle(comm):
    x = np.asarray(comm.rank_filled(N))
    np.testing.assert_array_equal(x, rows())
    assert comm.expected_allreduce_value() == ORACLE


def test_pingpong_swaps_even_odd_pairs(comm):
    out = np.asarray(comm.pingpong(comm.shard(rows())))
    expect = rows()[[r ^ 1 for r in range(WORLD)]]
    np.testing.assert_array_equal(out, expect)


def test_sendrecv_ring_shift(comm):
    x = comm.shard(rows())
    out = np.asarray(comm.sendrecv_ring(x, 1))
    # rank r's data lands on rank r+1: row r now holds r-1's values
    np.testing.assert_array_equal(out, rows()[(np.arange(WORLD) - 1) % WORLD])
    back = np.asarray(comm.sendrecv_ring(x, -1))
    np.testing.assert_array_equal(back, rows()[(np.arange(WORLD) + 1) % WORLD])


def test_all_gather_every_rank_sees_all_rows(comm):
    out = np.asarray(comm.all_gather(comm.shard(rows())))
    assert out.shape == (WORLD, WORLD, N)
    for r in range(WORLD):
        np.testing.assert_array_equal(out[r], rows())


def test_reduce_scatter_chunks(comm):
    data = np.random.default_rng(1).normal(size=(WORLD, WORLD * 4)).astype(np.float32)
    out = np.asarray(comm.reduce_scatter(comm.shard(data)))
    assert out.shape == (WORLD, 4)
    total = data.sum(axis=0)
    for r in range(WORLD):
        np.testing.assert_allclose(out[r], total[r * 4 : (r + 1) * 4], rtol=1e-5)


def test_all_to_all_transpose(comm):
    data = np.arange(WORLD * WORLD, dtype=np.float32).reshape(WORLD, WORLD)
    out = np.asarray(comm.all_to_all(comm.shard(data)))
    np.testing.assert_array_equal(out, data.T)


def test_shard_rejects_bad_leading_dim(comm):
    with pytest.raises(ValueError, match="leading dim"):
        comm.shard(np.ones((WORLD + 1, N)))
    with pytest.raises(ValueError, match="not in mesh"):
        Communicator(comm.mesh, "nope")


# -- in-shard_map primitives (ring engine reused by parallel/) -----------


def shmap(fn, mesh, n_in=1):
    spec = P("x", None)
    return jax.jit(
        shard_map(fn, mesh=mesh, in_specs=(spec,) * n_in, out_specs=spec)
    )


def test_ring_schedule_generic_combine(comm):
    # max over the ring == pmax: exercises ring_schedule with a non-sum op
    def per_rank(local):
        return ring.ring_schedule(local, "x", lambda acc, inc, _s: jnp.maximum(acc, inc))

    x = comm.shard(rows())
    out = np.asarray(shmap(per_rank, comm.mesh)(x))
    np.testing.assert_array_equal(out, np.full((WORLD, N), WORLD - 1, np.float32))


def test_ring_reduce_scatter_and_all_gather_inverse(comm):
    data = np.random.default_rng(2).normal(size=(WORLD, WORLD * 8)).astype(np.float32)

    def per_rank(local):
        chunk = ring.ring_reduce_scatter(local[0], "x")  # (8,)
        return ring.ring_all_gather(chunk, "x", tiled=True)[None]

    out = np.asarray(shmap(per_rank, comm.mesh)(comm.shard(data)))
    total = data.sum(axis=0)
    for r in range(WORLD):
        np.testing.assert_allclose(out[r], total, rtol=1e-5)


def test_pairwise_exchange_needs_even_world():
    from hpc_patterns_tpu import topology

    mesh3 = topology.make_mesh({"y": -1})  # 8, even: build an odd submesh
    devs = jax.devices()[:3]
    import numpy as _np
    from jax.sharding import Mesh

    mesh_odd = Mesh(_np.asarray(devs), ("x",))

    def per_rank(local):
        return ring.pairwise_exchange(local, "x")

    with pytest.raises(ValueError, match="even axis size"):
        shard_map(
            per_rank, mesh=mesh_odd, in_specs=P("x", None), out_specs=P("x", None)
        )(jnp.ones((3, 4)))


def test_collectives_broadcast_and_ops(comm):
    x = comm.shard(rows())

    def bcast(local):
        return collectives.broadcast(local, "x", root=3)

    out = np.asarray(shmap(bcast, comm.mesh)(x))
    np.testing.assert_array_equal(out, np.full((WORLD, N), 3, np.float32))

    def pmaxmin(local):
        return collectives.allreduce(local, "x", "max") + collectives.allreduce(
            local, "x", "min"
        )

    out = np.asarray(shmap(pmaxmin, comm.mesh)(x))
    np.testing.assert_array_equal(out, np.full((WORLD, N), WORLD - 1, np.float32))

    with pytest.raises(ValueError, match="unknown reduce op"):
        collectives.allreduce(jnp.ones(4), "x", "xor")
