"""Known-clean: the quantized-decode discipline.

Quantize/dequant stay pure jnp inside the traced step (the scales are
computed, written, and consumed in the dispatch stream — no host ever
reads one mid-flight), and the weight dequant accessor is a cast plus
a fused multiply. The models/decode.py + models/transformer.py shapes,
minimized.
"""

import jax.numpy as jnp


def _quantize_rows(x):
    # per-row symmetric quantization, traced end to end: the scale is
    # a device value from birth to its lane-major pool slot
    amax = jnp.max(jnp.abs(x.astype(jnp.float32)), axis=-1)
    scale = jnp.maximum(amax / 127.0, 1e-8)
    q = jnp.clip(jnp.round(x.astype(jnp.float32) / scale[..., None]),
                 -127, 127)
    return q.astype(jnp.int8), scale


def _dequant(cache, scale):
    # dequant in the einsum stream: elementwise producers fuse, the
    # HBM read stays one byte per element
    return cache.astype(jnp.float32) * scale[..., None]


def _scale_write(pool, page_ids, offset, rows):
    # dispatch-only scatter, exactly like the page write it rides with
    return pool.at[page_ids, :, 0, offset].set(rows)


def matmul_weight(tree, name, dt):
    # dequant-at-use: int8 HBM read, f32 multiply fused into the
    # matmul stream, no host decision anywhere
    w = tree[name]
    qs = tree.get(name + "_qscale")
    if qs is None:
        return w.astype(dt)
    return (w.astype(jnp.float32) * qs.astype(jnp.float32)).astype(dt)
