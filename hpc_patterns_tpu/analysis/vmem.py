"""VMEM budget estimation for every ``pallas_call`` in the tree.

PR 8's third chip-only bug was a VMEM overflow: a kernel whose blocks
plus scratch exceeded Mosaic's scoped limit, invisible in interpret
mode and fatal at lowering on the chip. ROADMAP's PR 12 remainder asks
the same question forward ("the gather-into-VMEM scratch bound —
pages·P·D of pool dtype — may want grid streaming at long context").
This module answers it with a NUMBER before a chip session:

- every ``pl.pallas_call`` site is found statically (stdlib ``ast``,
  never importing the analyzed code — the jaxlint engine's rule);
- its VMEM working set is summed **symbolically**: BlockSpec block
  shapes (or whole-operand shapes where no block is given),
  ``scratch_shapes`` entries, and ``pl.run_scoped`` allocations inside
  the kernel body — each a polynomial over dimension symbols
  (``pages·P·D``), times the dtype's byte width;
- ``--vmem-report`` evaluates the polynomials under
  :data:`MODEL_DIMS` (the documented chip-serving model shape; unknown
  symbols fall back to :data:`DEFAULT_DIM` and are listed as ASSUMED)
  and prints per-kernel byte totals against each kernel's
  ``vmem_limit_bytes`` (or Mosaic's 16 MB default scoped limit);
- the ``vmem-budget`` rule (analysis/pallas_rules.py) fires only on
  totals resolvable from **literals alone** — the report informs, the
  rule never guesses.

The ``paged_flash`` row reproduces docs/quantization.md's bound: at
``pages·P = 16384``, ``D = 128``, an int8 pool costs ``2·pages·P·D``
= 4 MiB of gather scratch (8 MiB bf16) plus the f32 scale rows.
"""

from __future__ import annotations

import ast
from dataclasses import dataclass, field
from pathlib import Path

from hpc_patterns_tpu.analysis.core import ModuleInfo

#: Mosaic's default scoped VMEM limit — the budget a kernel that sets
#: no ``vmem_limit_bytes`` is lowered against.
DEFAULT_VMEM_LIMIT = 16 * 1024 * 1024

#: model dimension bindings for ``--vmem-report``: the chip-serving
#: shape the docs quote (docs/quantization.md: S_alloc = pages·P =
#: 16384, D = 128; comm benchmark shards ~MBs; fused-MLP flagship
#: blocks 512). A symbol absent here evaluates at
#: :data:`DEFAULT_DIM` and is listed as ASSUMED in the report row.
MODEL_DIMS: dict[str, int] = {
    # ring collectives (comm/fused.py): 8-device axis, ~MB shards
    # (the module's documented benchmark envelope)
    "size": 8,
    "m": 128, "n": 2048, "cn": 256, "n_pad": 2048, "k": 256,
    # attention/decode (ops/): chip serving shape
    "B": 8, "H": 16, "Hkv": 2, "g": 8, "D": 128, "d": 128,
    "P": 128, "pages": 128, "page_size": 128,
    "S": 16384, "S_alloc": 16384, "n_s": 128, "n_steps": 32, "U": 4,
    "block_q": 512, "block_k": 1024, "block_s": 512,
    "Tq": 8192, "Tk": 8192, "Tq_c": 2048,
    "n_q": 16, "n_q_c": 4, "n_kv": 8, "n_chunks": 4, "group": 8,
    # fused MLP (ops/fused_mlp.py): flagship rung
    "bt": 512, "bf": 512, "F": 4096, "N": 8192, "n_f": 8,
    # on-chip pipeline (concurrency/): bench chunk geometry
    "num_chunks": 64, "chunk_rows": 512,
    "rows": 512, "cols": 128,
}

#: fallback for dimension symbols with no model binding (flagged as
#: ASSUMED in the report, never silently trusted)
DEFAULT_DIM = 128

#: fallback byte width for unresolvable dtypes (``x.dtype`` — the
#: operand's runtime dtype); f32 is the tree's compute default
DEFAULT_DTYPE_BYTES = 4

_DTYPE_BYTES = {
    "float64": 8, "int64": 8, "uint64": 8,
    "float32": 4, "int32": 4, "uint32": 4,
    "bfloat16": 2, "float16": 2, "int16": 2, "uint16": 2,
    "int8": 1, "uint8": 1, "bool_": 1, "bool": 1,
    "float8_e4m3fn": 1, "float8_e5m2": 1,
}


# ---------------------------------------------------------------------------
# symbolic quantities: polynomials over dimension symbols
# ---------------------------------------------------------------------------
# A quantity is {(sym, sym, ...): coeff} — {(): 6} is the literal 6,
# {("pages", "P"): 2} is 2·pages·P. Add/Sub/Mul close over the form;
# anything else (floordiv, calls) becomes one ATOMIC symbol carrying
# its source text, so it still evaluates under a binding or falls to
# the assumed default.

Quantity = dict[tuple[str, ...], int]


def _q_const(n: int) -> Quantity:
    return {(): n}


def _q_sym(name: str) -> Quantity:
    return {(name,): 1}


def _q_add(a: Quantity, b: Quantity, sign: int = 1) -> Quantity:
    out = dict(a)
    for syms, c in b.items():
        out[syms] = out.get(syms, 0) + sign * c
        if out[syms] == 0:
            del out[syms]
    return out


def _q_mul(a: Quantity, b: Quantity) -> Quantity:
    out: Quantity = {}
    for sa, ca in a.items():
        for sb, cb in b.items():
            syms = tuple(sorted(sa + sb))
            out[syms] = out.get(syms, 0) + ca * cb
    return {k: v for k, v in out.items() if v}


def q_value(q: Quantity, bindings: dict[str, int],
            default: int = DEFAULT_DIM) -> tuple[int, set[str]]:
    """(numeric value, symbols that fell to the assumed default)."""
    total = 0
    assumed: set[str] = set()
    for syms, coeff in q.items():
        prod = coeff
        for s in syms:
            if s in bindings:
                prod *= bindings[s]
            else:
                assumed.add(s)
                prod *= default
        total += prod
    return total, assumed


def q_exact(q: Quantity) -> int | None:
    """The literal value, or None if any symbol survives."""
    if all(not syms for syms in q):
        return q.get((), 0)
    return None


# ---------------------------------------------------------------------------
# shared kernel-body discovery (pallas_rules.py imports these)
# ---------------------------------------------------------------------------


def scope_defs(mod: ModuleInfo, node: ast.AST) -> dict[str, ast.AST]:
    """Function definitions visible from ``node`` (enclosing scopes,
    innermost wins), by name."""
    out: dict[str, ast.AST] = {}
    chain = []
    cur: ast.AST | None = node
    while cur is not None:
        if isinstance(cur, (ast.FunctionDef, ast.AsyncFunctionDef,
                            ast.Module)):
            chain.append(cur)
        cur = mod.parents.get(cur)
    for scope in reversed(chain):
        for stmt in scope.body:
            if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
                out[stmt.name] = stmt
    return out


def resolve_kernel_arg(mod: ModuleInfo, expr: ast.AST, site: ast.AST,
                       depth: int = 0) -> list[ast.FunctionDef]:
    """FunctionDefs a ``pallas_call`` first argument can name: a local
    def, ``functools.partial(def, ...)``, or a kernel-factory call
    whose returns are followed."""
    if depth > 4:
        return []
    defs = scope_defs(mod, site)
    if isinstance(expr, ast.Name):
        fn = defs.get(expr.id)
        return [fn] if isinstance(fn, ast.FunctionDef) else []
    if isinstance(expr, ast.IfExp):
        return (resolve_kernel_arg(mod, expr.body, site, depth + 1)
                + resolve_kernel_arg(mod, expr.orelse, site, depth + 1))
    if not isinstance(expr, ast.Call):
        return []
    name = mod.resolve(expr.func) or ""
    if name == "functools.partial" and expr.args:
        return resolve_kernel_arg(mod, expr.args[0], site, depth + 1)
    if isinstance(expr.func, ast.Name):
        factory = defs.get(expr.func.id)
        if isinstance(factory, ast.FunctionDef):
            out: list[ast.FunctionDef] = []
            for node in ast.walk(factory):
                if isinstance(node, ast.Return) and node.value is not None:
                    out.extend(resolve_kernel_arg(
                        mod, node.value, node, depth + 1))
            return out
    return []


def _kernel_label(mod: ModuleInfo, call: ast.Call) -> str:
    """Human name for one pallas_call: the kernel function if
    resolvable, else the enclosing function."""
    fns = resolve_kernel_arg(mod, call.args[0], call) if call.args else []
    if fns:
        name = fns[0].name
        if name not in ("kernel", "_", "body"):
            return name
    cur = mod.parents.get(call)
    while cur is not None and not isinstance(
            cur, (ast.FunctionDef, ast.AsyncFunctionDef)):
        cur = mod.parents.get(cur)
    host = cur.name if cur is not None else "<module>"
    if fns and fns[0].name in ("kernel", "_", "body"):
        return f"{host}.{fns[0].name}"
    return host


# ---------------------------------------------------------------------------
# the estimator
# ---------------------------------------------------------------------------


@dataclass
class Component:
    """One VMEM contributor: a BlockSpec block, a whole-array operand,
    or a scratch allocation."""

    label: str              # "in[2]", "out[0]", "scratch[1]", "scoped"
    quantity: Quantity      # element count (polynomial)
    dtype_bytes: int | None  # None = unresolvable (model default)
    dtype_src: str = ""     # what the dtype expression said


@dataclass
class KernelEstimate:
    """Everything ``--vmem-report`` prints for one pallas_call."""

    kernel: str
    path: str
    line: int
    node: ast.AST
    components: list[Component] = field(default_factory=list)
    n_sems: int = 0
    limit_bytes: int = DEFAULT_VMEM_LIMIT
    limit_default: bool = True

    @property
    def exact_bytes(self) -> int | None:
        """A sound LOWER bound: the byte sum over components whose
        shape and dtype are both literal-resolvable, None when no
        component is. If this subset alone exceeds the limit the
        kernel is over regardless of the symbolic rest — the only
        judgement the vmem-budget rule makes (model-dim totals are
        the report's, never the gate's)."""
        total = None
        for c in self.components:
            if c.dtype_bytes is None:
                continue
            n = q_exact(c.quantity)
            if n is None:
                continue
            total = (total or 0) + n * c.dtype_bytes
        return total

    def model_bytes(self, bindings: dict[str, int] | None = None,
                    default_dim: int = DEFAULT_DIM,
                    dtype_default: int = DEFAULT_DTYPE_BYTES,
                    ) -> tuple[int, set[str]]:
        """(bytes under model bindings, assumed symbols). Components
        with unresolvable dtypes use ``dtype_default`` and contribute
        their dtype source to the assumed set."""
        bindings = MODEL_DIMS if bindings is None else bindings
        total = 0
        assumed: set[str] = set()
        for c in self.components:
            width = c.dtype_bytes
            if width is None:
                width = dtype_default
                assumed.add(c.dtype_src or "dtype?")
            n, syms = q_value(c.quantity, bindings, default_dim)
            assumed |= syms
            total += n * width
        return total, assumed


def _own_statements(scope: ast.AST) -> list[ast.AST]:
    """Statements belonging to ``scope`` itself, in source order:
    recurses into compound statements (if/for/with/try) but NOT into
    nested function/class bodies — another function's local
    ``n = 8192`` must never resolve this kernel's runtime ``n``."""
    out: list[ast.AST] = []

    def rec(stmts) -> None:
        for stmt in stmts:
            if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef,
                                 ast.ClassDef)):
                continue
            out.append(stmt)
            for field_name in ("body", "orelse", "finalbody"):
                rec(getattr(stmt, field_name, []))
            for h in getattr(stmt, "handlers", []):
                rec(h.body)

    rec(getattr(scope, "body", []))
    return out


class _Resolver:
    """Name resolution for shape/dtype expressions: simple assignments
    in the enclosing function chain plus module-level constants.
    Scope-correct: only each scope's OWN statements contribute, and a
    function's parameters shadow any outer binding (a parameter is
    runtime data — it must stay a symbol)."""

    def __init__(self, mod: ModuleInfo, site: ast.AST):
        self.mod = mod
        self.table: dict[str, ast.AST] = {}
        # outermost first so inner assignments win
        scopes: list[ast.AST] = [mod.tree]
        cur = mod.parents.get(site)
        chain = []
        while cur is not None:
            if isinstance(cur, (ast.FunctionDef, ast.AsyncFunctionDef)):
                chain.append(cur)
            cur = self.mod.parents.get(cur)
        scopes.extend(reversed(chain))
        for scope in scopes:
            if isinstance(scope, (ast.FunctionDef,
                                  ast.AsyncFunctionDef)):
                args = scope.args
                for p in (args.posonlyargs + args.args
                          + args.kwonlyargs
                          + ([args.vararg] if args.vararg else [])
                          + ([args.kwarg] if args.kwarg else [])):
                    self.table.pop(p.arg, None)
            for node in _own_statements(scope):
                if isinstance(node, ast.Assign) and len(
                        node.targets) == 1 and isinstance(
                            node.targets[0], ast.Name):
                    self.table[node.targets[0].id] = node.value

    def assignments_to(self, name: str, site: ast.AST
                       ) -> list[tuple[str, ast.AST]]:
        """All (kind, value) assignments to ``name`` in the function
        enclosing ``site`` (own statements only — nested defs are
        separate scopes), in source order — kind 'set' (=) or 'add'
        (+=). Spec lists are built incrementally; the estimate takes
        the union (the quantized branch's extra scratch counts: the
        budget question is the worst variant)."""
        fn = self.mod.parents.get(site)
        while fn is not None and not isinstance(
                fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
            fn = self.mod.parents.get(fn)
        if fn is None:
            return []
        out = []
        for node in _own_statements(fn):
            if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                    and isinstance(node.targets[0], ast.Name) \
                    and node.targets[0].id == name:
                out.append(("set", node.value))
            elif isinstance(node, ast.AugAssign) and isinstance(
                    node.target, ast.Name) and node.target.id == name \
                    and isinstance(node.op, ast.Add):
                out.append(("add", node.value))
        return out

    # -- quantities ------------------------------------------------------

    def quantity(self, node: ast.AST, depth: int = 0) -> Quantity:
        if depth > 12:
            return _q_sym(_srctext(node))
        if isinstance(node, ast.Constant):
            if isinstance(node.value, int):
                return _q_const(node.value)
            if node.value is None:
                # BlockSpec None dims: the grid axis the block drops
                return _q_const(1)
            return _q_sym(_srctext(node))
        if isinstance(node, ast.Name):
            tgt = self.table.get(node.id)
            if tgt is not None and not self._self_referential(
                    node.id, tgt):
                q = self.quantity(tgt, depth + 1)
                # a resolution that degenerated to the expression's
                # own text is no better than the name itself
                if q != _q_sym(_srctext(tgt)):
                    return q
            return _q_sym(node.id)
        if isinstance(node, ast.BinOp):
            left = self.quantity(node.left, depth + 1)
            right = self.quantity(node.right, depth + 1)
            if isinstance(node.op, ast.Add):
                return _q_add(left, right)
            if isinstance(node.op, ast.Sub):
                return _q_add(left, right, -1)
            if isinstance(node.op, ast.Mult):
                return _q_mul(left, right)
            if isinstance(node.op, (ast.FloorDiv, ast.Div)):
                le, re_ = q_exact(left), q_exact(right)
                if le is not None and re_ not in (None, 0):
                    return _q_const(int(le // re_))
            if isinstance(node.op, ast.Pow):
                le, re_ = q_exact(left), q_exact(right)
                if le is not None and re_ is not None and 0 <= re_ <= 8:
                    return _q_const(le ** re_)
            return _q_sym(_srctext(node))
        if isinstance(node, ast.UnaryOp) and isinstance(
                node.op, ast.USub):
            inner = self.quantity(node.operand, depth + 1)
            return _q_mul(inner, _q_const(-1))
        return _q_sym(_srctext(node))

    def _self_referential(self, name: str, expr: ast.AST) -> bool:
        return any(isinstance(n, ast.Name) and n.id == name
                   for n in ast.walk(expr))

    # -- dtypes ----------------------------------------------------------

    def dtype_bytes(self, node: ast.AST | None
                    ) -> tuple[int | None, str]:
        if node is None:
            return None, "dtype?"
        src = _srctext(node)
        name = self.mod.resolve(node)
        if name is None and isinstance(node, ast.Name):
            tgt = self.table.get(node.id)
            if tgt is not None:
                return self.dtype_bytes(tgt)
        if name:
            base = name.rsplit(".", 1)[-1]
            if base in _DTYPE_BYTES:
                return _DTYPE_BYTES[base], src
            if isinstance(node, ast.Name):
                tgt = self.table.get(node.id)
                if tgt is not None and _srctext(tgt) != src:
                    return self.dtype_bytes(tgt)
        return None, src

    # -- shapes of operand expressions ----------------------------------

    def shape_quantity(self, node: ast.AST, depth: int = 0
                       ) -> Quantity | None:
        """Element count of an operand expression, when its shape is
        statically visible (a reshape/zeros/full with resolvable
        dims); None otherwise."""
        if depth > 6:
            return None
        if isinstance(node, ast.Name):
            tgt = self.table.get(node.id)
            if tgt is not None and not self._self_referential(
                    node.id, tgt):
                return self.shape_quantity(tgt, depth + 1)
            return None
        if not isinstance(node, ast.Call):
            return None
        fname = (self.mod.resolve(node.func) or "").rsplit(".", 1)[-1]
        if isinstance(node.func, ast.Attribute) and \
                node.func.attr == "reshape":
            dims = node.args
            if len(dims) == 1 and isinstance(dims[0],
                                             (ast.Tuple, ast.List)):
                dims = dims[0].elts
            return self._dims_quantity(dims)
        if fname in ("zeros", "ones", "full", "empty",
                     "broadcast_to") and node.args:
            shp = node.args[0] if fname != "broadcast_to" else (
                node.args[1] if len(node.args) > 1 else None)
            if isinstance(shp, (ast.Tuple, ast.List)):
                return self._dims_quantity(shp.elts)
        return None

    def _dims_quantity(self, dims) -> Quantity | None:
        total = _q_const(1)
        for d in dims:
            q = self.quantity(d)
            # -1 in a reshape is an inferred dim: unknowable here
            if q_exact(q) == -1:
                return None
            total = _q_mul(total, q)
        return total


def _srctext(node: ast.AST) -> str:
    try:
        return ast.unparse(node)
    except Exception:  # pragma: no cover - unparse is total on 3.9+
        return type(node).__name__


# -- pallas_call dissection -------------------------------------------------


def _call_kwargs(call: ast.Call) -> dict[str, ast.AST]:
    return {kw.arg: kw.value for kw in call.keywords if kw.arg}


def _spec_entries(mod: ModuleInfo, res: _Resolver, node: ast.AST | None,
                  site: ast.AST, depth: int = 0,
                  seen: frozenset[str] = frozenset()
                  ) -> list[tuple[ast.AST, Quantity]]:
    """Flatten a specs expression into (spec-call, count) entries:
    literal lists, ``[spec] * n`` repeats, list comprehensions over
    ``range(U)``, and names built incrementally with ``= / +=``
    (``seen`` breaks self-referential rebuilds like
    ``out_specs = [out_specs, …]``)."""
    if node is None or depth > 8:
        return []
    if isinstance(node, (ast.List, ast.Tuple)):
        out = []
        for e in node.elts:
            out.extend(_spec_entries(mod, res, e, site, depth + 1, seen))
        return out
    if isinstance(node, ast.BinOp) and isinstance(node.op, ast.Mult):
        inner = _spec_entries(mod, res, node.left, site, depth + 1, seen)
        count = res.quantity(node.right)
        return [(spec, _q_mul(q, count)) for spec, q in inner]
    if isinstance(node, ast.ListComp) and len(node.generators) == 1 \
            and not node.generators[0].ifs:
        it = node.generators[0].iter
        count: Quantity = _q_sym(_srctext(it))
        if isinstance(it, ast.Call) and (
                mod.resolve(it.func) or "") == "range" and len(
                    it.args) == 1:
            count = res.quantity(it.args[0])
        inner = _spec_entries(mod, res, node.elt, site, depth + 1, seen)
        return [(spec, _q_mul(q, count)) for spec, q in inner]
    if isinstance(node, ast.Name):
        if node.id in seen:
            return []
        seen = seen | {node.id}
        parts = res.assignments_to(node.id, site)
        if parts:
            out = []
            for kind, value in parts:
                out.extend(_spec_entries(mod, res, value, site,
                                         depth + 1, seen))
            return out
        tgt = res.table.get(node.id)
        if tgt is not None:
            return _spec_entries(mod, res, tgt, site, depth + 1, seen)
        return []
    if isinstance(node, ast.Call):
        return [(node, _q_const(1))]
    if isinstance(node, ast.IfExp):
        # worst-case branch: the union covers both
        return (_spec_entries(mod, res, node.body, site, depth + 1, seen)
                + _spec_entries(mod, res, node.orelse, site, depth + 1,
                                seen))
    return []


def _resolve_spec_call(mod: ModuleInfo, res: _Resolver, call: ast.Call,
                       depth: int = 0) -> tuple[ast.AST | None,
                                                str, list[ast.AST]]:
    """(block-shape expr | None, memory-space name, args) of one
    BlockSpec-ish call, seeing through ``functools.partial`` aliases
    (``row = functools.partial(pl.BlockSpec, memory_space=VMEM)``)."""
    if depth > 4 or not isinstance(call, ast.Call):
        return None, "", []
    fname = (mod.resolve(call.func) or "").rsplit(".", 1)[-1]
    kwargs = _call_kwargs(call)
    space = ""
    if "memory_space" in kwargs:
        space = (mod.resolve(kwargs["memory_space"])
                 or _srctext(kwargs["memory_space"]))
    if fname == "BlockSpec":
        shape = call.args[0] if call.args else None
        return shape, space, list(call.args)
    if isinstance(call.func, ast.Name):
        tgt = res.table.get(call.func.id)
        if isinstance(tgt, ast.Call):
            t_name = (mod.resolve(tgt.func) or "").rsplit(".", 1)[-1]
            if t_name == "partial" and tgt.args:
                inner_kwargs = _call_kwargs(tgt)
                inner_space = ""
                if "memory_space" in inner_kwargs:
                    inner_space = (mod.resolve(
                        inner_kwargs["memory_space"])
                        or _srctext(inner_kwargs["memory_space"]))
                base = (mod.resolve(tgt.args[0]) or "").rsplit(
                    ".", 1)[-1]
                if base == "BlockSpec":
                    shape = call.args[0] if call.args else None
                    return shape, space or inner_space, list(call.args)
    return None, space, list(call.args)


def _block_quantity(res: _Resolver, shape: ast.AST) -> Quantity:
    if isinstance(shape, (ast.Tuple, ast.List)):
        return res._dims_quantity(shape.elts) or _q_sym(_srctext(shape))
    return _q_sym(_srctext(shape))


def _scratch_components(mod: ModuleInfo, res: _Resolver,
                        entries: list[tuple[ast.AST, Quantity]],
                        label: str) -> tuple[list[Component], int]:
    comps: list[Component] = []
    n_sems = 0
    for i, (call, count) in enumerate(entries):
        if not isinstance(call, ast.Call):
            continue
        name = mod.resolve(call.func) or _srctext(call.func)
        base = name.rsplit(".", 1)[-1]
        if "SemaphoreType" in name or base in ("DMA", "REGULAR",
                                               "BARRIER"):
            n_sems += 1
            continue
        if base in ("VMEM", "SMEM", "ANY"):
            if base != "VMEM":
                continue
            shape = call.args[0] if call.args else None
            dtype = call.args[1] if len(call.args) > 1 else None
            q = (_block_quantity(res, shape) if shape is not None
                 else _q_sym(_srctext(call)))
            width, dsrc = res.dtype_bytes(dtype)
            comps.append(Component(
                label=f"{label}[{i}]", quantity=_q_mul(q, count),
                dtype_bytes=width, dtype_src=dsrc))
    return comps, n_sems


def _out_shape_entries(node: ast.AST | None, res: _Resolver,
                       mod: ModuleInfo, site: ast.AST,
                       seen: frozenset[str] = frozenset()
                       ) -> list[ast.Call]:
    """ShapeDtypeStruct calls of an out_shape expression. ``seen``
    breaks self-referential rebuilds (``out_shape = [out_shape, …]``,
    the fused-MLP save-a pattern)."""
    if node is None:
        return []
    if isinstance(node, ast.Name):
        if node.id in seen:
            return []
        seen = seen | {node.id}
        parts = res.assignments_to(node.id, site)
        out: list[ast.Call] = []
        for _, value in parts:
            out.extend(_out_shape_entries(value, res, mod, site, seen))
        if out:
            return out
        tgt = res.table.get(node.id)
        return _out_shape_entries(tgt, res, mod, site, seen) \
            if tgt is not None else []
    if isinstance(node, (ast.Tuple, ast.List)):
        out = []
        for e in node.elts:
            out.extend(_out_shape_entries(e, res, mod, site, seen))
        return out
    if isinstance(node, ast.Call):
        base = (mod.resolve(node.func) or "").rsplit(".", 1)[-1]
        if base in ("ShapeDtypeStruct", "_sds"):
            return [node]
    return []


def estimate_call(mod: ModuleInfo, call: ast.Call) -> KernelEstimate:
    """The VMEM estimate for one ``pallas_call`` site."""
    res = _Resolver(mod, call)
    kwargs = _call_kwargs(call)
    grid_spec = kwargs.get("grid_spec")
    if isinstance(grid_spec, ast.Call):
        inner = _call_kwargs(grid_spec)
        for key in ("in_specs", "out_specs", "scratch_shapes", "grid"):
            if key in inner and key not in kwargs:
                kwargs[key] = inner[key]

    est = KernelEstimate(
        kernel=_kernel_label(mod, call), path=mod.path,
        line=call.lineno, node=call)

    # blocks: in_specs + out_specs with explicit shapes; whole-array
    # VMEM specs fall back to the operand/out_shape element counts
    out_shapes = _out_shape_entries(kwargs.get("out_shape"), res, mod,
                                    call)
    operands = _operand_exprs(mod, call)
    for label, key, fallback in (("in", "in_specs", operands),
                                 ("out", "out_specs", out_shapes)):
        entries = _spec_entries(mod, res, kwargs.get(key), call)
        # positional cursor into the operand list: a ``[spec] * n``
        # repeat covers n OPERANDS, so a whole-array entry must expand
        # to one component per covered operand (x AND w, not x twice)
        cursor = 0
        for i, (spec, count) in enumerate(entries):
            k = q_exact(count)
            width = k if isinstance(k, int) and k > 0 else 1
            shape, space, _ = _resolve_spec_call(mod, res, spec)
            space_base = (space or "").rsplit(".", 1)[-1]
            if space_base in ("SMEM", "ANY"):
                cursor += width
                continue
            if shape is not None:
                q = _block_quantity(res, shape)
                est.components.append(Component(
                    label=f"{label}[{i}]", quantity=_q_mul(q, count),
                    dtype_bytes=None, dtype_src=f"{label}[{i}].dtype"))
                cursor += width
                continue
            # whole-array residency: the operand / out_shape size per
            # covered position
            for j in range(width):
                pos = cursor + j
                fb = fallback[pos] if pos < len(fallback) else None
                comp = _whole_array_component(mod, res, fb,
                                              f"{label}[{pos}]")
                if comp is not None:
                    est.components.append(comp)
            cursor += width
    if not kwargs.get("out_specs") and out_shapes:
        for i, sds in enumerate(out_shapes):
            comp = _whole_array_component(mod, res, sds, f"out[{i}]")
            if comp is not None:
                est.components.append(comp)

    scratch = _spec_entries(mod, res, kwargs.get("scratch_shapes"), call)
    comps, n_sems = _scratch_components(mod, res, scratch, "scratch")
    est.components.extend(comps)
    est.n_sems += n_sems

    # run_scoped allocations inside the kernel body (the pipeline
    # kernels allocate their double-buffers there, not in the call)
    for fn in (resolve_kernel_arg(mod, call.args[0], call)
               if call.args else []):
        for node in ast.walk(fn):
            if isinstance(node, ast.Call) and (
                    mod.resolve(node.func) or "").rsplit(".", 1)[-1] \
                    == "run_scoped":
                # allocations ride as keywords (the tree's style) OR
                # positionally after the body — both count
                alloc_exprs = [kw.value for kw in node.keywords
                               if kw.arg] + list(node.args[1:])
                scoped = [(e, _q_const(1)) for e in alloc_exprs
                          if isinstance(e, ast.Call)]
                comps, n_sems = _scratch_components(
                    mod, _Resolver(mod, node), scoped, "scoped")
                est.components.extend(comps)
                est.n_sems += n_sems

    # the limit this kernel lowers against
    params = kwargs.get("compiler_params")
    if isinstance(params, ast.Call):
        limit = _call_kwargs(params).get("vmem_limit_bytes")
        if limit is not None:
            val = q_exact(res.quantity(limit))
            if val is not None:
                est.limit_bytes = val
                est.limit_default = False
    return est


def _operand_exprs(mod: ModuleInfo, call: ast.Call) -> list[ast.AST]:
    """The operand expressions of ``pl.pallas_call(...)(*operands)`` —
    the parent Call's arguments, when the site is called directly."""
    parent = mod.parents.get(call)
    if isinstance(parent, ast.Call) and parent.func is call:
        out: list[ast.AST] = []
        for a in parent.args:
            if isinstance(a, ast.Starred):
                inner = a.value
                if isinstance(inner, ast.Name):
                    res = _Resolver(mod, call)
                    parts = res.assignments_to(inner.id, call)
                    for _, value in parts:
                        if isinstance(value, (ast.List, ast.Tuple)):
                            out.extend(value.elts)
                continue
            out.append(a)
        return out
    return []


def _whole_array_component(mod: ModuleInfo, res: _Resolver,
                           expr: ast.AST | None,
                           label: str) -> Component | None:
    if expr is None:
        return Component(label=label,
                         quantity=_q_sym(f"{label}.elems"),
                         dtype_bytes=None, dtype_src=f"{label}.dtype")
    if isinstance(expr, ast.Call):
        base = (mod.resolve(expr.func) or "").rsplit(".", 1)[-1]
        if base in ("ShapeDtypeStruct", "_sds") and expr.args:
            shape = expr.args[0]
            dtype = expr.args[1] if len(expr.args) > 1 else None
            q = (res._dims_quantity(shape.elts)
                 if isinstance(shape, (ast.Tuple, ast.List)) else None)
            width, dsrc = res.dtype_bytes(dtype)
            return Component(
                label=label,
                quantity=q if q is not None else _q_sym(_srctext(shape)),
                dtype_bytes=width, dtype_src=dsrc)
    q = res.shape_quantity(expr)
    if q is None:
        q = _q_sym(f"elems({_srctext(expr)})")
    return Component(label=label, quantity=q, dtype_bytes=None,
                     dtype_src=f"{_srctext(expr)}.dtype")


def estimate_module(mod: ModuleInfo) -> list[KernelEstimate]:
    """One estimate per ``pallas_call`` in the module, source order."""
    out = []
    for node in ast.walk(mod.tree):
        if isinstance(node, ast.Call) and (
                mod.resolve(node.func) or "").rsplit(".", 1)[-1] \
                == "pallas_call":
            out.append(estimate_call(mod, node))
    return sorted(out, key=lambda e: e.line)


def estimate_paths(paths) -> list[KernelEstimate]:
    """Estimates across files/dirs (the ``--vmem-report`` driver)."""
    from hpc_patterns_tpu.analysis.core import iter_python_files

    out: list[KernelEstimate] = []
    for f in iter_python_files(paths):
        try:
            mod = ModuleInfo.parse(f)
        except SyntaxError:
            continue
        out.extend(estimate_module(mod))
    return out


def format_vmem_table(estimates: list[KernelEstimate],
                      bindings: dict[str, int] | None = None,
                      root: str | Path | None = None) -> str:
    """The ``--vmem-report`` table: per-kernel byte totals under the
    model dims, against each kernel's limit, ASSUMED symbols named."""
    lines = [
        f"{'kernel':<28} {'site':<34} {'vmem bytes':>12} "
        f"{'limit':>10} {'frac':>6}  notes",
    ]
    for est in estimates:
        total, assumed = est.model_bytes(bindings)
        path = est.path
        if root is not None:
            try:
                path = str(Path(est.path).relative_to(root))
            except ValueError:
                pass
        site = f"{path}:{est.line}"
        frac = total / est.limit_bytes if est.limit_bytes else 0.0
        notes = []
        if est.limit_default:
            notes.append("default-limit")
        if est.n_sems:
            notes.append(f"{est.n_sems} sem(s)")
        if assumed:
            shown = sorted(assumed)[:4]
            more = len(assumed) - len(shown)
            notes.append("ASSUMED " + ",".join(shown)
                         + (f" +{more}" if more > 0 else ""))
        flag = " OVER" if total > est.limit_bytes else ""
        lines.append(
            f"{est.kernel[:28]:<28} {site[-34:]:<34} {total:>12,} "
            f"{est.limit_bytes // (1024 * 1024):>8}MB {frac:>6.2f}"
            f"{flag}  {'; '.join(notes)}")
    if not estimates:
        lines.append("(no pallas_call sites found)")
    return "\n".join(lines)


def vmem_summary(estimates: list[KernelEstimate]) -> dict:
    """JSON-able rollup for the ``kind=analysis`` RunLog record."""
    rows = []
    n_over = 0
    for est in estimates:
        total, assumed = est.model_bytes()
        over = total > est.limit_bytes
        n_over += bool(over)
        rows.append({
            "kernel": est.kernel,
            "line": est.line,
            "bytes": total,
            "limit": est.limit_bytes,
            "over": over,
            "assumed": sorted(assumed),
        })
    return {"kernels": len(estimates), "over_limit": n_over,
            "rows": rows}
