"""App-level integration tests — the CTest analog (SURVEY.md §4.1).

The reference registers every miniapp binary as a CTest case under
``mpirun -np 4``; here every app main() runs in-process on the 8-device
virtual CPU mesh and must exit 0 with grep-able SUCCESS output.
"""

import json

import pytest

from hpc_patterns_tpu.apps import allreduce_app, common, pingpong_app


@pytest.mark.parametrize("extra", [[], ["-a"], ["--algorithm", "ring_chunked"],
                                   ["--algorithm", "fused"]])
def test_allreduce_app_exits_success(capsys, extra):
    # small -p keeps CPU-mesh runtime trivial; 3 reps for speed
    rc = allreduce_app.main(["-p", "10", "--repetitions", "3", "--warmup", "1"] + extra)
    out = capsys.readouterr().out
    assert rc == 0, out
    assert "SUCCESS" in out
    assert "Passed 0" in out and "Passed 7" in out


def test_allreduce_app_typed_variant_int(capsys):
    # the typed CTest axis (mpi-sycl/CMakeLists.txt:4-5): int must be exact
    rc = allreduce_app.main(["-p", "8", "--dtype", "int32", "--repetitions", "2"])
    assert rc == 0
    assert "SUCCESS" in capsys.readouterr().out


def test_allreduce_app_writes_jsonl(tmp_path, capsys):
    log = tmp_path / "run.jsonl"
    rc = allreduce_app.main(["-p", "8", "--repetitions", "2", "--log", str(log)])
    assert rc == 0
    records = [json.loads(l) for l in log.read_text().splitlines()]
    (res,) = [r for r in records if r.get("kind") == "result"]
    assert res["success"] and res["world"] == 8
    assert res["busbw_gbps"] > 0
    capsys.readouterr()


def test_allreduce_app_host_memory_kind_falls_back(capsys):
    rc = allreduce_app.main(["-p", "8", "-H", "--repetitions", "2"])
    out = capsys.readouterr().out
    assert rc == 0
    # CPU mesh has no pinned_host kind; the app logs the fallback
    assert "SUCCESS" in out


def test_allreduce_app_size_sweep(tmp_path, capsys):
    # the BASELINE metric protocol: busbw-vs-size curve per algorithm,
    # every point validated against the analytic oracle
    log = tmp_path / "sweep.jsonl"
    rc = allreduce_app.main(["--sweep", "--min-p", "3", "-p", "5",
                             "--repetitions", "2", "--warmup", "1",
                             "--log", str(log)])
    out = capsys.readouterr().out
    assert rc == 0, out
    assert "sweep: 12/12 points passed" in out
    records = [json.loads(l) for l in log.read_text().splitlines()
               if '"result"' in l]
    assert len(records) == 12  # 4 algorithms x p in {3,4,5}
    algs = {r["name"] for r in records}
    assert algs == {"allreduce[ring]", "allreduce[ring_chunked]",
                    "allreduce[collective]", "allreduce[fused]"}
    assert all(r["success"] and r["world"] == 8 for r in records)
    sizes = sorted(r["elements"] for r in records
                   if r["name"] == "allreduce[collective]")
    assert sizes == [8, 16, 32]


def test_allreduce_sweep_bad_range_fails(capsys):
    rc = allreduce_app.main(["--sweep", "--min-p", "9", "-p", "5"])
    out = capsys.readouterr().out
    assert rc == 1
    assert "FAILURE" in out


def test_pingpong_app_sweep(capsys):
    rc = pingpong_app.main(["--min-p", "3", "-p", "6", "--repetitions", "2"])
    out = capsys.readouterr().out
    assert rc == 0
    assert out.count("pingpong n=2^") == 4
    assert "SUCCESS" in out and "MISMATCH" not in out


def test_bus_bandwidth_normalization():
    # world=2: busbw = algbw * 2*(1)/2 = algbw
    assert common.allreduce_bus_bandwidth_gbps(1e9, 1.0, 2) == pytest.approx(1.0)
    # world=8: factor 2*7/8
    assert common.allreduce_bus_bandwidth_gbps(1e9, 1.0, 8) == pytest.approx(1.75)
    assert common.allreduce_bus_bandwidth_gbps(1e9, 1.0, 1) == 0.0


def test_make_communicator_world_guards():
    c = common.make_communicator("cpu", -1)
    assert c.size == 8
    c = common.make_communicator("cpu", 5, even=True)
    assert c.size == 4  # odd world drops to even (reference precondition)
    from hpc_patterns_tpu.topology import TopologyError

    with pytest.raises(TopologyError):
        common.make_communicator("cpu", 99)


class TestEvalApp:
    def test_synthetic_eval_bounds(self, capsys):
        from hpc_patterns_tpu.apps import eval_app

        code = eval_app.main(
            ["--batches", "2", "--batch", "2", "--seq", "16",
             "--d-model", "32", "--n-layers", "1", "--vocab", "64"]
        )
        out = capsys.readouterr().out
        assert code == 0
        assert "perplexity" in out and "SUCCESS" in out

    def test_chunked_eval_matches_dense(self, capsys):
        # same perplexity with and without --loss-chunk (logits-free)
        import re

        from hpc_patterns_tpu.apps import eval_app

        def ppl(extra):
            code = eval_app.main(
                ["--batches", "2", "--batch", "2", "--seq", "16",
                 "--d-model", "32", "--n-layers", "1", "--vocab", "64"]
                + extra
            )
            out = capsys.readouterr().out
            assert code == 0, out
            return float(re.search(r"nll (\d+\.\d+)", out).group(1))

        assert abs(ppl([]) - ppl(["--loss-chunk", "16"])) < 1e-3

    def test_token_file_eval(self, capsys, tmp_path):
        import numpy as np

        from hpc_patterns_tpu.apps import eval_app
        from hpc_patterns_tpu.utils.data import write_token_file

        path = tmp_path / "toks.bin"
        write_token_file(path, np.arange(2000) % 64, "uint16")
        code = eval_app.main(
            ["--data", str(path), "--batches", "2", "--batch", "2",
             "--seq", "16", "--d-model", "32", "--n-layers", "1",
             "--vocab", "64"]
        )
        out = capsys.readouterr().out
        assert code == 0, out
        assert "SUCCESS" in out

    def test_train_then_eval_roundtrip(self, capsys, tmp_path):
        # the README lifecycle: train --checkpoint-dir (no resume-check)
        # with a cosine schedule, then eval restores WITHOUT an
        # optimizer template (scheduled opt states have a different
        # pytree structure than the default constant-LR one)
        from hpc_patterns_tpu.apps import eval_app, train_app

        ck = tmp_path / "ck"
        shape = ["--batch", "2", "--seq", "16", "--d-model", "32",
                 "--n-layers", "1", "--vocab", "64"]
        code = train_app.main(
            ["--steps", "3", "--schedule", "cosine", "--warmup-steps", "1",
             "--checkpoint-dir", str(ck), *shape]
        )
        assert code == 0, capsys.readouterr().out
        code = eval_app.main(
            ["--checkpoint-dir", str(ck), "--batches", "2", *shape]
        )
        out = capsys.readouterr().out
        assert code == 0, out
        assert "restored step 3" in out and "SUCCESS" in out

    def test_eval_checkpoint_config_mismatch_fails_cleanly(self, capsys,
                                                           tmp_path):
        from hpc_patterns_tpu.apps import eval_app, train_app

        ck = tmp_path / "ck"
        code = train_app.main(
            ["--steps", "1", "--checkpoint-dir", str(ck), "--batch", "2",
             "--seq", "16", "--d-model", "32", "--n-layers", "1",
             "--vocab", "64"]
        )
        assert code == 0
        code = eval_app.main(
            ["--checkpoint-dir", str(ck), "--batches", "1", "--batch", "2",
             "--seq", "16", "--d-model", "64", "--n-layers", "1",
             "--vocab", "64"]
        )
        out = capsys.readouterr().out
        assert code == 1
        assert "ERROR" in out and "FAILURE" in out

    def test_eval_missing_checkpoint_fails_cleanly(self, capsys, tmp_path):
        from hpc_patterns_tpu.apps import eval_app

        code = eval_app.main(
            ["--checkpoint-dir", str(tmp_path / "nope"), "--batches", "1",
             "--batch", "2", "--seq", "16", "--d-model", "32",
             "--n-layers", "1", "--vocab", "64"]
        )
        out = capsys.readouterr().out
        assert code == 1
        assert "ERROR" in out and "FAILURE" in out


class TestServeApp:
    def test_serve_oracle_exact_success(self, capsys):
        from hpc_patterns_tpu.apps import serve_app

        code = serve_app.main(
            ["--requests", "5", "--slots", "2", "--budget", "8",
             "--prompt-len", "9", "--chunk", "2"]
        )
        out = capsys.readouterr().out
        assert code == 0, out
        assert "oracle[exact] ok" in out and "SUCCESS" in out
        assert "bubble" in out and "prefill compiles" in out

    def test_serve_sampled_and_mix(self, capsys):
        # the production knobs through the CLI: mixed prompt lengths,
        # sampled decode, bucketed admission — sampled oracle stays
        # standalone-exact (per-request key streams)
        from hpc_patterns_tpu.apps import serve_app

        code = serve_app.main(
            ["--requests", "5", "--slots", "2", "--budget", "6",
             "--prompt-len", "10", "--chunk", "2", "--prompt-mix",
             "--temperature", "0.9", "--top-k", "8", "--seed", "4"]
        )
        out = capsys.readouterr().out
        assert code == 0, out
        assert "oracle[sampled exact] ok" in out and "SUCCESS" in out

    def test_serve_eos_and_int8(self, capsys):
        from hpc_patterns_tpu.apps import serve_app

        code = serve_app.main(
            ["--requests", "4", "--slots", "2", "--budget", "8",
             "--prompt-len", "9", "--eos-id", "3",
             "--kv-dtype", "int8"]
        )
        out = capsys.readouterr().out
        assert code == 0, out
        assert "SUCCESS" in out

    def test_serve_pool_too_small_fails_cleanly(self, capsys):
        from hpc_patterns_tpu.apps import serve_app

        code = serve_app.main(
            ["--requests", "2", "--slots", "1", "--budget", "8",
             "--prompt-len", "9", "--pool-pages", "1"]
        )
        out = capsys.readouterr().out
        assert code == 1
        assert "FAILURE" in out
