"""pallaslint: the in-kernel DMA/semaphore/VMEM discipline rules.

PR 8's review pass found five chip-only bugs in the fused ring kernels
**by hand**: a re-waited send semaphore (deadlock at size>=3), a gather
write landing in a still-live reduce-scatter recv slot, a VMEM
overflow, a shared ``collective_id`` between concurrent kernels, and a
dtype-discipline hole. All five are invisible in interpret mode —
jax's dma-discharge interpreter serializes DMAs and leaves semaphores
inert — and all five are exactly the class that kills scarce chip
sessions. This module makes them machine-checkable at review time,
the same move jaxlint (PR 4) and shardlint (PR 6) made for Python-level
and SPMD-level hazards.

The centerpiece is a **semaphore-ledger abstract interpreter** over
kernel-body functions (still pure stdlib ``ast`` — analyzed code is
never imported). Kernel bodies are discovered from ``pl.pallas_call``
sites (through ``functools.partial`` wrappers and kernel-factory
functions), then executed abstractly:

- refs (parameters, ``run_scoped`` scratch, unpacked ``*refs``) are
  symbolic; ``ref.at[i]``/``ref[i]`` with concrete ``i`` are slots;
- ``make_async_copy``/``make_async_remote_copy`` build DMA records;
  ``.start()`` adds one outstanding signal per semaphore channel,
  ``.wait()``/``.wait_send()``/``.wait_recv()`` consume the oldest —
  per ``(semaphore, slot)``, so the wait-through-a-fresh-descriptor
  pattern (``get_dma(slot, i).wait()``) accounts correctly;
- Python ring loops unroll; opaque trip counts (the ring ``size``)
  are modeled at :data:`MODEL_RING` devices — the smallest size where
  the PR 8 drain bug manifests is 3, and the model covers it;
- opaque branch predicates fork the analysis (one consistent
  true/false assignment per path, capped); a construct the interpreter
  cannot order soundly makes the kernel **abstain** — no findings,
  never a guess.

Rules (fixtures: ``tests/fixtures/analysis/bad_/clean_pallas_dma.py``,
``bad_/clean_vmem_budget.py``):

- ``dma-sem-balance``   — a wait on a semaphore slot with no
                          outstanding signal (the PR 8 drain
                          double-wait: a slot-reuse wait already
                          consumed it — deadlock on chip), and DMA
                          signals left outstanding at kernel exit
                          (the DMA outlives the kernel's scratch);
- ``dma-slot-reuse``    — a buffer slot rewritten (locally or by a
                          landing DMA) while an un-waited DMA still
                          reads or writes it, and one scratch buffer
                          receiving DMAs under two semaphore families
                          (the PR 8 gather-into-``rs_recv`` shape:
                          dedicated-slot discipline, checkable);
- ``collective-id-collision`` — a hand-picked integer
                          ``collective_id`` (must come from the
                          ``ops.tiling.collective_id`` registry), or
                          two call sites sharing one id/registry name;
- ``kernel-dtype-cast`` — a widened matmul
                          (``preferred_element_type=...``) stored into
                          a kernel ref without ``.astype(ref.dtype)``
                          — interpret mode forgives the implicit
                          cast; Mosaic need not;
- ``vmem-budget``       — a kernel whose literal-resolvable BlockSpec
                          blocks + scratch exceed its
                          ``vmem_limit_bytes`` (estimator:
                          ``analysis/vmem.py``; the symbolic/model
                          side is ``--vmem-report``).
"""

from __future__ import annotations

import ast
from typing import Iterable

from hpc_patterns_tpu.analysis.core import (
    AnalysisConfig,
    Finding,
    ModuleInfo,
    Rule,
    register,
)
from hpc_patterns_tpu.analysis import vmem as vmem_mod

#: modeled ring size for opaque loop bounds (``range(1, size)`` where
#: ``size`` is a runtime mesh axis size). 4 is the smallest even size
#: strictly above the PR 8 drain bug's manifestation threshold (3), so
#: both parities of the alternating send slot are exercised.
MODEL_RING = 4

_PATH_CAP = 64        # max forked paths per kernel before abstaining
_STEP_CAP = 200_000   # abstract-interpreter step budget per path
_DEPTH_CAP = 16       # inline depth for helper calls

_DMA_BUILDERS = frozenset({"make_async_copy", "make_async_remote_copy"})
_DMA_WAITS = frozenset({"wait", "wait_send", "wait_recv"})


# ---------------------------------------------------------------------------
# abstract values
# ---------------------------------------------------------------------------


class _Opaque:
    """An unresolvable value (runtime data, jnp results, mesh sizes)."""

    __slots__ = ("label",)

    def __init__(self, label: str = "?"):
        self.label = label

    def __repr__(self):  # pragma: no cover - debug aid
        return f"<? {self.label}>"


class _Ref:
    """A kernel ref (operand, output, scratch buffer, or semaphore)."""

    __slots__ = ("name",)

    def __init__(self, name: str):
        self.name = name


class _AtProxy:
    __slots__ = ("ref",)

    def __init__(self, ref: _Ref):
        self.ref = ref


class _AbsTuple:
    """The ``*refs`` parameter tuple: unknown length; slicing keeps the
    abstraction, unpacking materializes fresh refs named by target."""

    __slots__ = ("prefix",)

    def __init__(self, prefix: str):
        self.prefix = prefix


class _Func:
    __slots__ = ("fndef", "closure")

    def __init__(self, fndef, closure=None):
        self.fndef = fndef
        self.closure = closure or {}


class _Partial:
    __slots__ = ("func", "args", "kwargs")

    def __init__(self, func, args, kwargs):
        self.func = func
        self.args = args
        self.kwargs = kwargs


class _When:
    __slots__ = ("cond",)

    def __init__(self, cond):
        self.cond = cond


class _Method:
    __slots__ = ("obj", "attr")

    def __init__(self, obj, attr):
        self.obj = obj
        self.attr = attr


class _DMA:
    """One async copy: semaphore channels + src/dst slots."""

    __slots__ = ("src", "dst", "send_key", "recv_key", "remote",
                 "node", "start_node", "send_waited", "recv_waited",
                 "started")

    def __init__(self, src, dst, send_key, recv_key, remote, node):
        self.src = src            # (ref_name, idx) or None
        self.dst = dst
        self.send_key = send_key  # (sem_name, idx) or None
        self.recv_key = recv_key
        self.remote = remote
        self.node = node
        self.start_node = None
        self.send_waited = False
        self.recv_waited = False
        self.started = False

    def start_line(self) -> int:
        node = self.start_node or self.node
        return getattr(node, "lineno", 0)


class _Abstain(Exception):
    """The kernel contains a construct the interpreter cannot order
    soundly (opaque semaphore slot, DMA under an unresolvable loop):
    drop every finding for this kernel rather than guess."""


class _NeedFork(Exception):
    def __init__(self, key: str):
        self.key = key


class _Return(Exception):
    def __init__(self, value):
        self.value = value


def _slot_of(value) -> tuple[str, object] | None:
    """(ref_name, idx) of a slot-ish value; idx is an int, ``"*"``
    (whole ref) or ``"?"`` (unresolvable index)."""
    if isinstance(value, _Ref):
        return (value.name, "*")
    if isinstance(value, tuple) and len(value) == 2 and isinstance(
            value[0], str):
        return value
    return None


def _overlaps(a, b) -> bool:
    """Conservative slot overlap: same ref and (either side whole, or
    equal concrete indices). Opaque indices never overlap — precision
    over recall, so model-limit noise can't fake findings."""
    if a is None or b is None or a[0] != b[0]:
        return False
    ia, ib = a[1], b[1]
    if ia == "?" or ib == "?":
        return False
    return ia == "*" or ib == "*" or ia == ib


# ---------------------------------------------------------------------------
# kernel-body discovery
# ---------------------------------------------------------------------------


def _kernel_roots(mod: ModuleInfo) -> list[ast.FunctionDef]:
    """Kernel-body functions reachable from the module's
    ``pallas_call`` sites, deduped in source order."""
    roots: list[ast.FunctionDef] = []
    seen: set[int] = set()
    for node in ast.walk(mod.tree):
        if not isinstance(node, ast.Call):
            continue
        if (mod.resolve(node.func) or "").rsplit(".", 1)[-1] != \
                "pallas_call":
            continue
        if not node.args:
            continue
        for fn in vmem_mod.resolve_kernel_arg(mod, node.args[0], node):
            if id(fn) not in seen:
                seen.add(id(fn))
                roots.append(fn)
    return sorted(roots, key=lambda f: f.lineno)


# ---------------------------------------------------------------------------
# the ledger interpreter
# ---------------------------------------------------------------------------


class _KernelRun:
    """One abstract execution of one kernel body under one branch-memo
    assignment. The driver re-runs from the top for each fork."""

    def __init__(self, mod: ModuleInfo, memo: dict[str, bool]):
        self.mod = mod
        self.memo = memo
        self.module_env = self._module_env()
        self.steps = 0
        self._stack: list[str] = []
        # ledger: (sem_name, idx) -> outstanding signal count
        self.ledger: dict[tuple[str, object], int] = {}
        # start nodes per outstanding key, oldest first (exit findings
        # anchor at the start that was never drained)
        self.ledger_nodes: dict[tuple[str, object], list[ast.AST]] = {}
        self.inflight: list[_DMA] = []
        # dst buffer -> recv semaphore names seen (cross-phase rule)
        self.recv_sems_by_buf: dict[str, dict[str, ast.AST]] = {}
        self.findings: list[tuple[str, ast.AST, str]] = []
        # per-subject equality state for mode-switch predicates:
        # name -> (pinned constant | None, excluded constants)
        self._eq_state: dict[str, tuple[object, set]] = {}

    # -- environment -----------------------------------------------------

    def _module_env(self) -> dict[str, object]:
        env: dict[str, object] = {}
        for stmt in self.mod.tree.body:
            if isinstance(stmt, ast.FunctionDef):
                env[stmt.name] = _Func(stmt)
            elif isinstance(stmt, ast.Assign) and len(stmt.targets) == 1 \
                    and isinstance(stmt.targets[0], ast.Name):
                try:
                    env[stmt.targets[0].id] = ast.literal_eval(stmt.value)
                except (ValueError, SyntaxError):
                    pass
        return env

    # -- driver ----------------------------------------------------------

    def run(self, fn: ast.FunctionDef) -> None:
        env: dict[str, object] = {}
        for a in fn.args.posonlyargs + fn.args.args + fn.args.kwonlyargs:
            env[a.arg] = _Ref(a.arg)
        if fn.args.vararg is not None:
            env[fn.args.vararg.arg] = _AbsTuple(fn.args.vararg.arg)
        try:
            self.exec_block(fn.body, env)
        except _Return:
            pass
        self._check_exit(fn)

    def _check_exit(self, fn: ast.FunctionDef) -> None:
        for key, count in self.ledger.items():
            if count > 0:
                nodes = self.ledger_nodes.get(key) or [fn]
                self.findings.append((
                    "dma-sem-balance", nodes[0],
                    f"{count} DMA signal(s) on {_key_str(key)} left "
                    f"outstanding at kernel exit — the copy outlives "
                    f"the kernel's scratch (wait every started DMA "
                    f"exactly once before returning)",
                ))

    # -- statements ------------------------------------------------------

    def _tick(self):
        self.steps += 1
        if self.steps > _STEP_CAP:
            raise _Abstain

    def exec_block(self, stmts, env) -> None:
        for stmt in stmts:
            self.exec_stmt(stmt, env)

    def exec_stmt(self, stmt, env) -> None:
        self._tick()
        if isinstance(stmt, ast.FunctionDef):
            cond = self._when_cond(stmt, env)
            # closures are LIVE references (Python semantics): an inner
            # def must see outer names bound after its definition — the
            # loop-bound model binding (range/fori on an opaque size)
            # depends on this
            if cond is _SKIP:
                env[stmt.name] = _Func(stmt, env)
            elif cond:
                # pl.when(True): the body runs inline, now
                self.call_func(_Func(stmt, env), [], {})
            return
        if isinstance(stmt, (ast.Assign, ast.AnnAssign, ast.AugAssign)):
            self.exec_assign(stmt, env)
            return
        if isinstance(stmt, ast.Expr):
            self.eval(stmt.value, env)
            return
        if isinstance(stmt, ast.Return):
            raise _Return(self.eval(stmt.value, env)
                          if stmt.value is not None else None)
        if isinstance(stmt, ast.If):
            test = self.eval(stmt.test, env)
            branch = self._as_bool(test, stmt.test)
            self.exec_block(stmt.body if branch else stmt.orelse, env)
            return
        if isinstance(stmt, ast.For):
            self.exec_for(stmt, env)
            return
        if isinstance(stmt, ast.While):
            test = self.eval(stmt.test, env)
            if isinstance(test, _Opaque):
                if _block_has_dma(stmt.body):
                    raise _Abstain
                return
            # concrete while loops don't occur in kernel bodies here;
            # bound them defensively
            spins = 0
            while self._as_bool(test, stmt.test):
                self.exec_block(stmt.body, env)
                test = self.eval(stmt.test, env)
                spins += 1
                if spins > 64:
                    raise _Abstain
            return
        if isinstance(stmt, (ast.Pass, ast.Break, ast.Continue,
                             ast.Import, ast.ImportFrom, ast.Global,
                             ast.Nonlocal, ast.ClassDef)):
            return
        if isinstance(stmt, (ast.With, ast.Try)):
            if isinstance(stmt, ast.With):
                self.exec_block(stmt.body, env)
            else:
                self.exec_block(stmt.body, env)
                self.exec_block(stmt.finalbody, env)
            return
        if isinstance(stmt, (ast.Assert, ast.Delete, ast.Raise)):
            return
        # unknown statement kind: ignore (no DMA semantics)

    def _when_cond(self, fn: ast.FunctionDef, env):
        """``@pl.when(cond)`` decorator handling: _SKIP when the def is
        a plain function, else the (concrete) branch decision."""
        for dec in fn.decorator_list:
            if isinstance(dec, ast.Call) and (
                    self.mod.resolve(dec.func) or ""
            ).rsplit(".", 1)[-1] == "when" and dec.args:
                cond = self.eval(dec.args[0], env)
                return self._as_bool(cond, dec.args[0])
        return _SKIP

    def _as_bool(self, value, node) -> bool:
        if not isinstance(value, _Opaque):
            return bool(value)
        key = ast.dump(node)
        if key in self.memo:
            result = self.memo[key]
        else:
            # mode-switch predicates (``mode == "overlap"`` /
            # ``mode != "overlap_out"``) must stay mutually consistent
            # within one path: a factory kernel's branches on one
            # opaque subject would otherwise fork into impossible
            # combinations (two different equalities both true) and
            # fake ledger findings
            result = self._eq_family(node)
            if result is None:
                raise _NeedFork(key)
        self._note_eq(node, result)
        return result

    @staticmethod
    def _eq_parts(node) -> tuple[str, object, bool] | None:
        """(subject, constant, is_eq) of a single ``name ==/!= const``
        comparison, else None."""
        if not (isinstance(node, ast.Compare) and len(node.ops) == 1
                and isinstance(node.left, ast.Name)
                and isinstance(node.comparators[0], ast.Constant)):
            return None
        op = node.ops[0]
        if not isinstance(op, (ast.Eq, ast.NotEq)):
            return None
        return (node.left.id, node.comparators[0].value,
                isinstance(op, ast.Eq))

    def _eq_family(self, node) -> bool | None:
        parts = self._eq_parts(node)
        if parts is None:
            return None
        subject, const, is_eq = parts
        pinned, excluded = self._eq_state.get(subject, (None, set()))
        if pinned is not None:
            return (pinned == const) if is_eq else (pinned != const)
        if const in excluded:
            return False if is_eq else True
        return None

    def _note_eq(self, node, result: bool) -> None:
        parts = self._eq_parts(node)
        if parts is None:
            return
        subject, const, is_eq = parts
        pinned, excluded = self._eq_state.get(subject, (None, set()))
        if is_eq == result:        # == True or != False: pin
            pinned = const
        else:                      # == False or != True: exclude
            excluded = excluded | {const}
        self._eq_state[subject] = (pinned, excluded)

    def exec_for(self, stmt: ast.For, env) -> None:
        it = self.eval(stmt.iter, env)
        if isinstance(it, _Opaque):
            if _block_has_dma(stmt.body):
                raise _Abstain
            return
        if isinstance(it, range):
            items = list(it)
        elif isinstance(it, (list, tuple)):
            items = list(it)
        else:
            if _block_has_dma(stmt.body):
                raise _Abstain
            return
        for item in items:
            self._bind(stmt.target, item, env)
            self.exec_block(stmt.body, env)
        self.exec_block(stmt.orelse, env)

    def exec_assign(self, stmt, env) -> None:
        if isinstance(stmt, ast.AugAssign):
            value = _Opaque("aug")
            if isinstance(stmt.target, ast.Name):
                cur = env.get(stmt.target.id)
                rhs = self.eval(stmt.value, env)
                if isinstance(cur, int) and isinstance(rhs, int):
                    value = _arith(type(stmt.op), cur, rhs)
                elif isinstance(cur, list) and isinstance(
                        stmt.op, ast.Add) and isinstance(rhs, list):
                    value = cur + rhs
                env[stmt.target.id] = value
            elif isinstance(stmt.target, ast.Subscript):
                self.eval(stmt.value, env)
                self._store_subscript(stmt.target, _Opaque("aug"), env)
            return
        value = self.eval(stmt.value, env) if stmt.value is not None \
            else None
        targets = stmt.targets if isinstance(stmt, ast.Assign) \
            else [stmt.target]
        for tgt in targets:
            self._bind(tgt, value, env)

    def _bind(self, tgt, value, env) -> None:
        if isinstance(tgt, ast.Name):
            env[tgt.id] = value
            return
        if isinstance(tgt, (ast.Tuple, ast.List)):
            names = tgt.elts
            if isinstance(value, _AbsTuple):
                for e in names:
                    if isinstance(e, ast.Name):
                        env[e.id] = _Ref(e.id)
                return
            if isinstance(value, (list, tuple)) and len(value) == len(
                    names):
                for e, v in zip(names, value):
                    self._bind(e, v, env)
                return
            for e in names:
                if isinstance(e, ast.Name):
                    env[e.id] = _Opaque(e.id)
            return
        if isinstance(tgt, ast.Subscript):
            self._store_subscript(tgt, value, env)

    def _store_subscript(self, tgt: ast.Subscript, value, env) -> None:
        base = self.eval(tgt.value, env)
        if isinstance(base, list):
            idx = self.eval(tgt.slice, env)
            if isinstance(idx, int) and -len(base) <= idx < len(base):
                base[idx] = value
            return
        if isinstance(base, _Ref):
            idx = self._slot_index(tgt.slice, env)
            self._check_write((base.name, idx), tgt)

    def _slot_index(self, node, env):
        idx = self.eval(node, env)
        if isinstance(idx, int):
            return idx
        if isinstance(idx, (tuple, list)):
            # ref[i, ...]: a concrete LEADING element indexes the slot
            # axis; anything else (ref[:, ds(...)], ref[opaque, 0])
            # degrades to a whole-ref touch — conservative overlap,
            # never a guessed slot
            if idx and isinstance(idx[0], int):
                return idx[0]
            return "*"
        if isinstance(idx, _Opaque):
            return "?"
        return "*"

    # -- hazards ---------------------------------------------------------

    def _check_write(self, slot, node) -> None:
        """A local store (or a landing DMA, via start) into ``slot``:
        flag when an un-waited in-flight DMA still reads (send pending)
        or writes (recv pending) the same bytes."""
        for dma in self.inflight:
            if not dma.started:
                continue
            if not dma.send_waited and _overlaps(dma.src, slot):
                self.findings.append((
                    "dma-slot-reuse", node,
                    f"write to {_key_str(slot)} while the DMA started "
                    f"at line {dma.start_line()} is still reading it "
                    f"(send semaphore not waited) — the copy may send "
                    f"the NEW bytes",
                ))
            if not dma.recv_waited and _overlaps(dma.dst, slot):
                self.findings.append((
                    "dma-slot-reuse", node,
                    f"write to {_key_str(slot)} while the DMA started "
                    f"at line {dma.start_line()} is still landing "
                    f"there (recv semaphore not waited) — last writer "
                    f"is a race",
                ))

    def _check_read(self, slot, node) -> None:
        for dma in self.inflight:
            if dma.started and not dma.recv_waited and _overlaps(
                    dma.dst, slot):
                self.findings.append((
                    "dma-slot-reuse", node,
                    f"read of {_key_str(slot)} before the DMA started "
                    f"at line {dma.start_line()} has landed (recv "
                    f"semaphore not waited) — interpret mode "
                    f"serializes this; chips do not",
                ))

    # -- expressions -----------------------------------------------------

    def eval(self, node, env):
        self._tick()
        if node is None:
            return None
        if isinstance(node, ast.Constant):
            return node.value
        if isinstance(node, ast.Name):
            if node.id in env:
                return env[node.id]
            if node.id in self.module_env:
                return self.module_env[node.id]
            if node.id in ("True", "False", "None"):  # pragma: no cover
                return {"True": True, "False": False, "None": None}[
                    node.id]
            return _Opaque(node.id)
        if isinstance(node, ast.Attribute):
            base = self.eval(node.value, env)
            if isinstance(base, _Ref) and node.attr == "at":
                return _AtProxy(base)
            if isinstance(base, (_DMA, list)):
                return _Method(base, node.attr)
            return _Opaque(node.attr)
        if isinstance(node, ast.Subscript):
            return self._load_subscript(node, env)
        if isinstance(node, ast.BinOp):
            left = self.eval(node.left, env)
            right = self.eval(node.right, env)
            if isinstance(left, (int, float)) and isinstance(
                    right, (int, float)):
                return _arith(type(node.op), left, right)
            if isinstance(left, list) and isinstance(right, list) \
                    and isinstance(node.op, ast.Add):
                return left + right
            if isinstance(left, list) and isinstance(right, int) \
                    and isinstance(node.op, ast.Mult):
                return left * right
            return _Opaque("binop")
        if isinstance(node, ast.UnaryOp):
            v = self.eval(node.operand, env)
            if isinstance(v, (int, float)) and isinstance(
                    node.op, ast.USub):
                return -v
            if isinstance(node.op, ast.Not) and not isinstance(
                    v, _Opaque):
                return not v
            return _Opaque("unary")
        if isinstance(node, ast.Compare):
            return self._compare(node, env)
        if isinstance(node, ast.BoolOp):
            vals = [self.eval(v, env) for v in node.values]
            if any(isinstance(v, _Opaque) for v in vals):
                return _Opaque("boolop")
            if isinstance(node.op, ast.And):
                return all(bool(v) for v in vals)
            return any(bool(v) for v in vals)
        if isinstance(node, ast.IfExp):
            test = self.eval(node.test, env)
            return self.eval(
                node.body if self._as_bool(test, node.test)
                else node.orelse, env)
        if isinstance(node, (ast.Tuple, ast.List)):
            return [self.eval(e, env) for e in node.elts]
        if isinstance(node, ast.Call):
            return self.eval_call(node, env)
        if isinstance(node, ast.Lambda):
            return _Opaque("lambda")
        if isinstance(node, (ast.ListComp, ast.GeneratorExp)):
            return self._comprehension(node, env)
        if isinstance(node, ast.Slice):
            return slice(
                self.eval(node.lower, env) if node.lower else None,
                self.eval(node.upper, env) if node.upper else None,
                self.eval(node.step, env) if node.step else None,
            )
        if isinstance(node, ast.JoinedStr):
            return _Opaque("fstring")
        if isinstance(node, ast.Starred):
            return self.eval(node.value, env)
        return _Opaque(type(node).__name__)

    def _compare(self, node: ast.Compare, env):
        left = self.eval(node.left, env)
        result: object = True
        for op, comp in zip(node.ops, node.comparators):
            right = self.eval(comp, env)
            if isinstance(op, (ast.Is, ast.IsNot)):
                # the one judgement opaque values support: identity
                # against None (``if b_ref is not None`` unpacking)
                if left is None or right is None:
                    same = left is None and right is None
                    if isinstance(left, _Opaque) or isinstance(
                            right, _Opaque):
                        return _Opaque("is")
                    result = same if isinstance(op, ast.Is) else not same
                    left = right
                    continue
                if isinstance(left, _Opaque) or isinstance(
                        right, _Opaque):
                    return _Opaque("is")
                result = (left is right) if isinstance(op, ast.Is) \
                    else (left is not right)
                left = right
                continue
            if isinstance(left, _Opaque) or isinstance(right, _Opaque):
                return _Opaque("cmp")
            try:
                result = _COMPARES[type(op)](left, right)
            except (TypeError, KeyError):
                return _Opaque("cmp")
            if not result:
                return False
            left = right
        return result

    def _comprehension(self, node, env):
        if len(node.generators) != 1 or node.generators[0].ifs:
            return _Opaque("comp")
        gen = node.generators[0]
        it = self.eval(gen.iter, env)
        if not isinstance(it, (range, list, tuple)):
            return _Opaque("comp")
        out = []
        sub = dict(env)
        for item in it:
            self._bind(gen.target, item, sub)
            out.append(self.eval(node.elt, sub))
        return out

    def _load_subscript(self, node: ast.Subscript, env):
        base = self.eval(node.value, env)
        if isinstance(base, _AtProxy):
            idx = self._slot_index(node.slice, env)
            if idx == "?":
                return (base.ref.name, "?")
            return (base.ref.name, idx)
        if isinstance(base, _Ref):
            idx = self._slot_index(node.slice, env)
            if isinstance(idx, int):
                self._check_read((base.name, idx), node)
            return _Opaque(f"{base.name}[]")
        if isinstance(base, (list, tuple)):
            idx = self.eval(node.slice, env)
            if isinstance(idx, int):
                if -len(base) <= idx < len(base):
                    return base[idx]
                return _Opaque("index")
            if isinstance(idx, slice):
                try:
                    return list(base)[idx]
                except (TypeError, ValueError):
                    return _Opaque("slice")
            return _Opaque("index")
        if isinstance(base, _AbsTuple):
            idx = self.eval(node.slice, env)
            if isinstance(idx, slice):
                return _AbsTuple(base.prefix)
            if isinstance(idx, int):
                return _Ref(f"{base.prefix}[{idx}]")
            return _Opaque("abs-index")
        return _Opaque("subscript")

    # -- calls -----------------------------------------------------------

    def eval_call(self, node: ast.Call, env):
        # method dispatch on abstract objects first (DMA ops, lists)
        if isinstance(node.func, ast.Attribute):
            base = self.eval(node.func.value, env)
            if isinstance(base, _DMA):
                return self._dma_op(base, node.func.attr, node)
            if isinstance(base, list):
                return self._list_op(base, node.func.attr, node, env)
            if isinstance(base, _AtProxy):
                return _Opaque("at-method")
        func_val = None
        if isinstance(node.func, ast.Name):
            func_val = env.get(node.func.id,
                               self.module_env.get(node.func.id))
        if isinstance(func_val, _Method):
            # a bound DMA/list method stashed in a variable
            # (``w = d.wait_send; w()``) must dispatch, not dissolve
            # into an opaque call that silently drops the wait
            if isinstance(func_val.obj, _DMA):
                return self._dma_op(func_val.obj, func_val.attr, node)
            if isinstance(func_val.obj, list):
                return self._list_op(func_val.obj, func_val.attr, node,
                                     env)
            return _Opaque("method")
        if isinstance(func_val, _Func):
            args = [self.eval(a, env) for a in node.args]
            kwargs = {kw.arg: self.eval(kw.value, env)
                      for kw in node.keywords if kw.arg}
            return self.call_func(func_val, args, kwargs)
        if isinstance(func_val, _Partial):
            args = [self.eval(a, env) for a in node.args]
            kwargs = {kw.arg: self.eval(kw.value, env)
                      for kw in node.keywords if kw.arg}
            merged = list(func_val.args) + args
            mk = dict(func_val.kwargs)
            mk.update(kwargs)
            if isinstance(func_val.func, _Func):
                return self.call_func(func_val.func, merged, mk)
            return _Opaque("partial-call")
        if isinstance(func_val, _When):
            args = [self.eval(a, env) for a in node.args]
            if args and isinstance(args[0], _Func):
                if self._as_bool(func_val.cond, node):
                    return self.call_func(args[0], [], {})
            return None
        name = (self.mod.resolve(node.func) or "").rsplit(".", 1)[-1]
        return self._intrinsic(name, node, env)

    def call_func(self, fn: _Func, args, kwargs):
        fndef = fn.fndef
        env = dict(fn.closure)
        params = (fndef.args.posonlyargs + fndef.args.args)
        defaults = fndef.args.defaults
        # positional params, then defaults for the tail
        n_no_default = len(params) - len(defaults)
        for i, p in enumerate(params):
            if i < len(args):
                env[p.arg] = args[i]
            elif p.arg in kwargs:
                env[p.arg] = kwargs.pop(p.arg)
            elif i >= n_no_default:
                env[p.arg] = self.eval(defaults[i - n_no_default], env)
            else:
                env[p.arg] = _Opaque(p.arg)
        if fndef.args.vararg is not None:
            env[fndef.args.vararg.arg] = list(args[len(params):])
        kw_defaults = fndef.args.kw_defaults
        for i, p in enumerate(fndef.args.kwonlyargs):
            if p.arg in kwargs:
                env[p.arg] = kwargs.pop(p.arg)
            elif kw_defaults[i] is not None:
                env[p.arg] = self.eval(kw_defaults[i], env)
            else:
                env[p.arg] = _Opaque(p.arg)
        if len(self._stack) >= _DEPTH_CAP:
            raise _Abstain
        self._stack.append(fndef.name)
        try:
            self.exec_block(fndef.body, env)
            return None
        except _Return as r:
            return r.value
        finally:
            self._stack.pop()

    def _list_op(self, base: list, attr: str, node: ast.Call, env):
        args = [self.eval(a, env) for a in node.args]
        if attr == "append":
            base.append(args[0] if args else _Opaque("append"))
            return None
        if attr == "extend" and args and isinstance(args[0], list):
            base.extend(args[0])
            return None
        if attr == "pop":
            if base:
                return base.pop(args[0] if args and isinstance(
                    args[0], int) else -1)
            return _Opaque("pop")
        return _Opaque(f"list.{attr}")

    # -- DMA semantics ---------------------------------------------------

    def _sem_key(self, value, node) -> tuple[str, object]:
        slot = _slot_of(value)
        if slot is None:
            raise _Abstain
        if slot[1] == "?":
            raise _Abstain
        return slot

    def _build_dma(self, node: ast.Call, env, remote: bool):
        args = [self.eval(a, env) for a in node.args]
        kwargs = {kw.arg: self.eval(kw.value, env)
                  for kw in node.keywords if kw.arg}
        if remote:
            src = kwargs.get("src_ref", args[0] if len(args) > 0 else None)
            dst = kwargs.get("dst_ref", args[1] if len(args) > 1 else None)
            send = kwargs.get("send_sem",
                              args[2] if len(args) > 2 else None)
            recv = kwargs.get("recv_sem",
                              args[3] if len(args) > 3 else None)
            send_key = self._sem_key(send, node)
            recv_key = self._sem_key(recv, node)
        else:
            src = kwargs.get("src_ref", args[0] if len(args) > 0 else None)
            dst = kwargs.get("dst_ref", args[1] if len(args) > 1 else None)
            sem = kwargs.get("sem", args[2] if len(args) > 2 else None)
            send_key = None
            recv_key = self._sem_key(sem, node)
        return _DMA(_slot_of(src), _slot_of(dst), send_key, recv_key,
                    remote, node)

    def _signal(self, key, node) -> None:
        self.ledger[key] = self.ledger.get(key, 0) + 1
        self.ledger_nodes.setdefault(key, []).append(node)

    def _consume(self, key, node, what: str) -> bool:
        if self.ledger.get(key, 0) <= 0:
            self.findings.append((
                "dma-sem-balance", node,
                f"{what} on {_key_str(key)} with no outstanding signal "
                f"— an earlier wait already consumed it (the PR 8 "
                f"drain double-wait) or the matching start is missing; "
                f"on chip this wait never returns",
            ))
            return False
        self.ledger[key] -= 1
        nodes = self.ledger_nodes.get(key)
        if nodes:
            nodes.pop(0)
        return True

    def _dma_op(self, dma: _DMA, attr: str, node: ast.Call):
        if attr == "start":
            dma.started = True
            dma.start_node = node
            if dma.send_key is not None:
                self._signal(dma.send_key, node)
            if dma.recv_key is not None:
                self._signal(dma.recv_key, node)
            if dma.dst is not None:
                self._check_write(dma.dst, node)
                self._track_recv_family(dma, node)
            if dma.src is not None:
                self._check_read(dma.src, node)
            self.inflight.append(dma)
            return None
        if attr in ("wait", "wait_send", "wait_recv"):
            if attr in ("wait", "wait_send") and dma.send_key is not None:
                if self._consume(dma.send_key, node, f".{attr}()"):
                    self._mark_waited(dma.send_key, "send")
            if attr in ("wait", "wait_recv") and dma.recv_key is not None:
                if self._consume(dma.recv_key, node, f".{attr}()"):
                    self._mark_waited(dma.recv_key, "recv")
            return None
        return _Opaque(f"dma.{attr}")

    def _mark_waited(self, key, channel: str) -> None:
        """The oldest in-flight DMA on this semaphore channel landed."""
        for dma in self.inflight:
            if channel == "send" and dma.send_key == key \
                    and not dma.send_waited:
                dma.send_waited = True
                return
            if channel == "recv" and dma.recv_key == key \
                    and not dma.recv_waited:
                dma.recv_waited = True
                if dma.send_key is None:
                    # a local copy has ONE semaphore: its wait means
                    # the whole transfer (read side included) is done
                    dma.send_waited = True
                return

    def _track_recv_family(self, dma: _DMA, node) -> None:
        if dma.dst is None or dma.recv_key is None or not dma.remote:
            return
        buf = dma.dst[0]
        fams = self.recv_sems_by_buf.setdefault(buf, {})
        sem_name = dma.recv_key[0]
        if sem_name not in fams:
            if fams:
                other = next(iter(fams))
                self.findings.append((
                    "dma-slot-reuse", node,
                    f"scratch {buf!r} receives DMAs under two "
                    f"semaphore families ({other!r}, {sem_name!r}) — "
                    f"phase-crossed recv slots (the PR 8 gather-into-"
                    f"reduce-scatter-slot bug); give each phase a "
                    f"dedicated recv buffer",
                ))
            fams[sem_name] = node

    # -- intrinsics ------------------------------------------------------

    def _intrinsic(self, name: str, node: ast.Call, env):
        if name in _DMA_BUILDERS:
            return self._build_dma(
                node, env, remote=(name == "make_async_remote_copy"))
        if name == "when":
            cond = self.eval(node.args[0], env) if node.args else True
            return _When(cond)
        if name == "run_scoped":
            return self._run_scoped(node, env)
        if name == "fori_loop":
            return self._fori(node, env)
        if name == "partial":
            args = [self.eval(a, env) for a in node.args]
            kwargs = {kw.arg: self.eval(kw.value, env)
                      for kw in node.keywords if kw.arg}
            if args and isinstance(args[0], _Func):
                return _Partial(args[0], args[1:], kwargs)
            return _Opaque("partial")
        if name == "range":
            return self._range(node, env)
        if name == "rem":
            args = [self.eval(a, env) for a in node.args]
            if len(args) == 2 and all(
                    isinstance(a, int) for a in args) and args[1] != 0:
                return args[0] % args[1]
            return _Opaque("rem")
        if name == "len":
            args = [self.eval(a, env) for a in node.args]
            if args and isinstance(args[0], (list, tuple)):
                return len(args[0])
            return _Opaque("len")
        if name in ("min", "max", "abs", "int"):
            args = [self.eval(a, env) for a in node.args]
            if args and all(isinstance(a, (int, float)) for a in args):
                return {"min": min, "max": max, "abs": abs,
                        "int": int}[name](*args)
            return _Opaque(name)
        # anything else (jnp ops, pl.ds, program_id, axis_index …):
        # evaluate args for their ref-read side conditions, result is
        # opaque data
        for a in node.args:
            self.eval(a, env)
        for kw in node.keywords:
            self.eval(kw.value, env)
        return _Opaque(name)

    def _range(self, node: ast.Call, env):
        vals = []
        for i, a in enumerate(node.args):
            v = self.eval(a, env)
            if isinstance(v, _Opaque):
                # the ring-size model: an opaque bound (the runtime
                # mesh axis size) unrolls at MODEL_RING devices; a
                # plain-Name bound is also BOUND to the model so
                # ``s < size - 1`` inside the loop resolves
                # consistently
                v = MODEL_RING
                if isinstance(a, ast.Name):
                    env[a.id] = MODEL_RING
            if not isinstance(v, int):
                return _Opaque("range")
            vals.append(v)
        try:
            return range(*vals)
        except (TypeError, ValueError):
            return _Opaque("range")

    def _run_scoped(self, node: ast.Call, env):
        body = self.eval(node.args[0], env) if node.args else None
        if not isinstance(body, _Func):
            if _block_has_dma([node]):
                raise _Abstain
            return _Opaque("run_scoped")
        # allocations bind to the body's params: keywords by name, any
        # positional extras by position (both API forms are legal)
        params = body.fndef.args.posonlyargs + body.fndef.args.args
        args = [_Ref(p.arg) for p in params[:len(node.args) - 1]]
        kwargs = {kw.arg: _Ref(kw.arg) for kw in node.keywords if kw.arg}
        return self.call_func(body, args, kwargs)

    def _fori(self, node: ast.Call, env):
        if len(node.args) < 4:
            return _Opaque("fori")
        lo = self.eval(node.args[0], env)
        hi = self.eval(node.args[1], env)
        body = self.eval(node.args[2], env)
        carry = self.eval(node.args[3], env)
        if isinstance(lo, _Opaque):
            lo = 0
        if isinstance(hi, _Opaque):
            hi = MODEL_RING
            if isinstance(node.args[1], ast.Name):
                env[node.args[1].id] = MODEL_RING
        if not (isinstance(lo, int) and isinstance(hi, int)
                and isinstance(body, _Func)):
            if _block_has_dma([node]):
                raise _Abstain
            return _Opaque("fori")
        for i in range(lo, min(hi, lo + 64)):
            carry = self.call_func(body, [i, carry], {})
        return carry


_SKIP = object()

_COMPARES = {
    ast.Eq: lambda a, b: a == b,
    ast.NotEq: lambda a, b: a != b,
    ast.Lt: lambda a, b: a < b,
    ast.LtE: lambda a, b: a <= b,
    ast.Gt: lambda a, b: a > b,
    ast.GtE: lambda a, b: a >= b,
    ast.In: lambda a, b: a in b,
    ast.NotIn: lambda a, b: a not in b,
}


def _arith(op, a, b):
    try:
        if op is ast.Add:
            return a + b
        if op is ast.Sub:
            return a - b
        if op is ast.Mult:
            return a * b
        if op is ast.FloorDiv:
            return a // b
        if op is ast.Mod:
            return a % b
        if op is ast.Div:
            return a / b
        if op is ast.Pow:
            return a ** b
        if op is ast.BitXor:
            return a ^ b
    except (ZeroDivisionError, TypeError, OverflowError):
        pass
    return _Opaque("arith")


def _key_str(key: tuple[str, object]) -> str:
    name, idx = key
    if idx == "*":
        return name
    return f"{name}[{idx}]"


def _block_has_dma(stmts) -> bool:
    """Whether a statement/expression list contains DMA-relevant calls
    — the abstain trigger for loops the interpreter cannot unroll."""
    for stmt in stmts:
        for node in ast.walk(stmt):
            if isinstance(node, ast.Attribute) and node.attr in (
                    _DMA_WAITS | {"start"} | _DMA_BUILDERS):
                return True
            if isinstance(node, ast.Name) and node.id in _DMA_BUILDERS:
                return True
    return False


# ---------------------------------------------------------------------------
# driver: forked runs per kernel, cached per module
# ---------------------------------------------------------------------------


_LEDGER_CACHE: dict[tuple[str, int], list[tuple[str, ast.AST, str]]] = {}


def ledger_findings(mod: ModuleInfo) -> list[tuple[str, ast.AST, str]]:
    """All ledger/slot findings for one module: every kernel body, every
    branch-memo path, deduped. A kernel that abstains contributes
    nothing (conservative — silence is never a guess)."""
    cache_key = (mod.path, hash(mod.source))
    if cache_key in _LEDGER_CACHE:
        return _LEDGER_CACHE[cache_key]
    out: list[tuple[str, ast.AST, str]] = []
    for fn in _kernel_roots(mod):
        out.extend(_analyze_kernel(mod, fn))
    _LEDGER_CACHE[cache_key] = out
    if len(_LEDGER_CACHE) > 256:
        _LEDGER_CACHE.pop(next(iter(_LEDGER_CACHE)))
    return out


def _analyze_kernel(mod: ModuleInfo,
                    fn: ast.FunctionDef) -> list[tuple[str, ast.AST, str]]:
    pending: list[dict[str, bool]] = [{}]
    done = 0
    findings: list[tuple[str, ast.AST, str]] = []
    seen: set[tuple[str, int, str]] = set()
    while pending:
        memo = pending.pop()
        run = _KernelRun(mod, memo)
        run._stack = []
        try:
            run.run(fn)
        except _NeedFork as f:
            if done + len(pending) >= _PATH_CAP:
                return []  # fork explosion: abstain
            pending.append({**memo, f.key: True})
            pending.append({**memo, f.key: False})
            continue
        except _Abstain:
            return []
        except RecursionError:  # pragma: no cover - defensive
            return []
        done += 1
        if done > _PATH_CAP:
            return []
        for kind, node, msg in run.findings:
            key = (kind, getattr(node, "lineno", 0), msg)
            if key not in seen:
                seen.add(key)
                findings.append((kind, node, msg))
    return findings


# ---------------------------------------------------------------------------
# the rules
# ---------------------------------------------------------------------------


@register
class DmaSemBalanceRule(Rule):
    """The PR 8 drain bug class, statically: the semaphore ledger must
    balance — every wait consumes exactly one outstanding signal, and
    no signal outlives the kernel. A wait with nothing outstanding is
    a deadlock on chip (one signal per DMA; a slot-reuse wait may have
    consumed it steps earlier); a signal left at exit is a DMA racing
    the kernel's scratch teardown."""

    name = "dma-sem-balance"
    family = "pallaslint"
    summary = ("kernel DMA semaphore ledger imbalance: double-wait, "
               "wait-without-signal, or signals outstanding at exit")
    hint = ("wait every started DMA exactly once per channel; after a "
            "slot-reuse wait chain, drain ONLY the still-outstanding "
            "tail (comm/fused.py's dmas[-1].wait_send() pattern)")

    def check(self, mod: ModuleInfo, config: AnalysisConfig
              ) -> Iterable[Finding]:
        for kind, node, msg in ledger_findings(mod):
            if kind == self.name:
                yield self.finding(mod, node, msg)


@register
class DmaSlotReuseRule(Rule):
    """Dedicated-slot discipline, checkable: no write may land in a
    slot an un-waited DMA still reads or writes, no read may consume a
    slot whose DMA has not landed, and no scratch buffer may serve as
    the recv target of two DMA phases (the PR 8 gather-into-
    ``rs_recv`` bug — nothing orders one phase's completion after the
    other's remote consumption)."""

    name = "dma-slot-reuse"
    family = "pallaslint"
    summary = ("scratch slot reused while a DMA is in flight, or one "
               "recv buffer shared across DMA phases")
    hint = ("wait the in-flight DMA's semaphore before touching its "
            "slot, and give each ring phase its own recv scratch "
            "(comm/fused.py's rs_recv/ag_recv split)")

    def check(self, mod: ModuleInfo, config: AnalysisConfig
              ) -> Iterable[Finding]:
        for kind, node, msg in ledger_findings(mod):
            if kind == self.name:
                yield self.finding(mod, node, msg)


@register
class CollectiveIdCollisionRule(Rule):
    """Same-id collective kernels share barrier/DMA state on chip: two
    concurrent kernels with one ``collective_id`` hang or corrupt, and
    interpret mode never notices. The ``ops.tiling.collective_id``
    registry assigns ids by name (collisions impossible by
    construction); this rule flags hand-picked integers and any two
    call sites sharing an id or a registry name in one module."""

    name = "collective-id-collision"
    family = "pallaslint"
    summary = ("hand-picked or colliding collective_id (use the "
               "ops.tiling.collective_id registry)")
    hint = ("pass collective_id=tiling.collective_id('<unique.name>') "
            "— the registry makes two concurrent kernels sharing an "
            "id impossible by construction")

    # duplicate detection is PER MODULE (the engine's deliberate
    # scope, rules.py module docstring); the cross-module half of the
    # invariant — no two call sites anywhere registering one name —
    # is test-pinned over the whole package in tests/test_analysis.py

    def check(self, mod: ModuleInfo, config: AnalysisConfig
              ) -> Iterable[Finding]:
        seen: dict[object, ast.AST] = {}
        for node in ast.walk(mod.tree):
            if not isinstance(node, ast.Call):
                continue
            for kw in node.keywords:
                if kw.arg != "collective_id":
                    continue
                key = self._id_key(mod, kw.value)
                if key is None:
                    continue
                kind, value = key
                if kind == "literal":
                    yield self.finding(
                        mod, kw.value,
                        f"hand-picked collective_id={value}: ids by "
                        f"convention collide silently — register a "
                        f"name with ops.tiling.collective_id instead",
                    )
                if key in seen:
                    yield self.finding(
                        mod, kw.value,
                        f"collective_id {value!r} already used at "
                        f"line {seen[key].lineno} in this module — "
                        f"concurrent same-id kernels share barrier "
                        f"state (the PR 8 shared-id bug)",
                    )
                else:
                    seen[key] = kw.value

    @staticmethod
    def _id_key(mod: ModuleInfo, value: ast.AST):
        if isinstance(value, ast.Constant) and isinstance(
                value.value, int):
            return ("literal", value.value)
        if isinstance(value, ast.Call):
            base = (mod.resolve(value.func) or "").rsplit(".", 1)[-1]
            if base == "collective_id" and value.args and isinstance(
                    value.args[0], ast.Constant):
                return ("registry", value.args[0].value)
        return None


@register
class KernelDtypeCastRule(Rule):
    """The PR 8 dtype-discipline hole: a matmul widened with
    ``preferred_element_type=`` stored straight into a kernel ref.
    Interpret mode inserts the implicit narrowing cast; Mosaic's
    lowering need not agree (and a silent f32 landing in a bf16 ref is
    a parity break either way). The discipline —
    ``.astype(o_ref.dtype)`` on every widened store — is what the
    fused/flash kernels already do; this makes it checked."""

    name = "kernel-dtype-cast"
    family = "pallaslint"
    summary = ("widened matmul stored into a kernel ref without "
               ".astype(ref.dtype)")
    hint = ("end the store with .astype(<ref>.dtype) — the explicit "
            "cast is the contract interpret and Mosaic both honor")

    _WIDENING = frozenset({"dot", "dot_general", "einsum"})

    def check(self, mod: ModuleInfo, config: AnalysisConfig
              ) -> Iterable[Finding]:
        for node in ast.walk(mod.tree):
            if not (isinstance(node, ast.Assign)
                    and len(node.targets) == 1
                    and isinstance(node.targets[0], ast.Subscript)
                    and isinstance(node.targets[0].value, ast.Name)
                    and isinstance(node.value, ast.Call)):
                continue
            call = node.value
            base = (mod.resolve(call.func) or "").rsplit(".", 1)[-1]
            if base not in self._WIDENING:
                continue
            if not any(kw.arg == "preferred_element_type"
                       for kw in call.keywords):
                continue
            ref = node.targets[0].value.id
            yield self.finding(
                mod, node,
                f"widened {base} (preferred_element_type=...) stored "
                f"into {ref!r} without .astype({ref}.dtype) — "
                f"interpret mode forgives the implicit cast, Mosaic "
                f"need not",
            )


@register
class VmemBudgetRule(Rule):
    """A kernel whose VMEM working set exceeds its
    ``vmem_limit_bytes`` (or Mosaic's 16 MB default scoped limit when
    none is set) fails at lowering on chip — after the queue wait, on
    hardware the repo gets in scarce tunnel sessions. The estimator
    (``analysis/vmem.py``) sums BlockSpec blocks + scratch shapes;
    this rule fires only on totals resolvable from literals alone
    (symbolic shapes are ``--vmem-report``'s model-dimension
    territory, reported, never flagged)."""

    name = "vmem-budget"
    family = "pallaslint"
    summary = ("literal-resolvable kernel VMEM footprint exceeds its "
               "vmem_limit_bytes")
    hint = ("shrink the block/scratch shapes, stream the grid, or "
            "raise vmem_limit_bytes deliberately (and justify it — "
            "the physical budget is ~16 MB/core on most parts)")

    def check(self, mod: ModuleInfo, config: AnalysisConfig
              ) -> Iterable[Finding]:
        for est in vmem_mod.estimate_module(mod):
            if est.exact_bytes is None:
                continue
            if est.exact_bytes > est.limit_bytes:
                yield self.finding(
                    mod, est.node,
                    f"kernel {est.kernel!r} needs at least "
                    f"{est.exact_bytes:,} bytes of VMEM (the "
                    f"literal-resolvable blocks+scratch alone) "
                    f"against a {est.limit_bytes:,}-byte limit"
                    + (" (Mosaic default)" if est.limit_default
                       else ""),
                )
