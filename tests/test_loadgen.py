"""Open-loop load generator (harness/loadgen.py): every schedule is
deterministic given (params, seed), JSON round-trips exactly (the
replay contract chaos runs depend on), and each arrival process has
its defining statistical shape."""

import numpy as np
import pytest

from hpc_patterns_tpu.harness import loadgen

CLASSES = (
    loadgen.PriorityClass("interactive", 0, weight=1.0,
                          ttft_slo_s=0.5, tpot_slo_s=0.1,
                          deadline_s=2.0),
    loadgen.PriorityClass("batch", 1, weight=3.0),
)


def _sched(process="poisson", n=64, seed=0, **kw):
    return loadgen.make_schedule(
        n, rate_rps=50.0, classes=CLASSES, prompt_lens=(8, 16, 32),
        budgets=(4, 8, 16), budget_probs=(0.5, 0.3, 0.2),
        process=process, seed=seed, **kw)


class TestDeterminism:
    @pytest.mark.parametrize("process", ["poisson", "bursty", "diurnal"])
    def test_same_seed_same_schedule(self, process):
        assert _sched(process) == _sched(process)

    def test_different_seed_different_schedule(self):
        assert _sched(seed=1) != _sched(seed=2)

    def test_json_round_trip_is_exact(self):
        s = _sched("bursty", burst_factor=4.0)
        assert loadgen.Schedule.from_json(s.to_json()) == s
        # provenance rides along: the spec names what generated it
        assert s.spec["process"] == "bursty"
        assert s.spec["burst_factor"] == 4.0


class TestShapes:
    def test_arrivals_sorted_and_positive(self):
        for process in ("poisson", "bursty", "diurnal"):
            t = [r.t_arrival_s for r in _sched(process).requests]
            assert all(b >= a for a, b in zip(t, t[1:]))
            assert all(v > 0 for v in t)

    def test_poisson_rate_is_roughly_the_mean(self):
        s = _sched("poisson", n=512, seed=3)
        # 512 arrivals at 50 rps ≈ 10.24s span; generous 30% band
        assert 512 / s.duration_s == pytest.approx(50.0, rel=0.3)

    def test_bursty_is_burstier_than_poisson(self):
        # the defining property: the variance of per-window arrival
        # counts far exceeds the (Poisson) mean — the index of
        # dispersion separates the two processes cleanly
        def dispersion(sched):
            t = np.array([r.t_arrival_s for r in sched.requests])
            counts, _ = np.histogram(t, bins=max(4, int(t[-1] / 0.1)))
            return counts.var() / max(counts.mean(), 1e-9)

        poisson = dispersion(_sched("poisson", n=512, seed=5))
        bursty = dispersion(_sched("bursty", n=512, seed=5,
                                   burst_factor=16.0))
        assert bursty > 2.0 * poisson

    def test_diurnal_rate_modulates_with_the_period(self):
        s = _sched("diurnal", n=1024, seed=7, period_s=10.0, depth=0.9)
        t = np.array([r.t_arrival_s for r in s.requests])
        phase = (t % 10.0) / 10.0
        # peak half-period (sin > 0) must carry well more traffic
        peak = np.count_nonzero(phase < 0.5)
        trough = len(t) - peak
        assert peak > 1.5 * trough

    def test_classes_split_by_weight(self):
        s = _sched(n=512, seed=9)
        n_batch = sum(r.cls == "batch" for r in s.requests)
        assert n_batch / 512 == pytest.approx(0.75, abs=0.08)
        for r in s.requests:
            if r.cls == "interactive":
                assert r.priority == 0 and r.deadline_s == 2.0
            else:
                assert r.priority == 1 and r.deadline_s is None
            assert r.prompt_len in (8, 16, 32)
            assert r.max_new in (4, 8, 16)


def _shared(process="poisson", n=64, seed=0, **kw):
    base = dict(rate_rps=50.0, classes=CLASSES, n_templates=3,
                template_len=16, tail_lens=(3, 5, 8),
                budgets=(4, 8), process=process, seed=seed)
    base.update(kw)
    return loadgen.make_shared_prefix_schedule(n, **base)


class TestSharedPrefix:
    def test_deterministic_and_json_round_trips(self):
        assert _shared() == _shared()
        assert _shared(seed=1) != _shared(seed=2)
        s = _shared("bursty", tree_frac=0.3, burst_factor=4.0)
        back = loadgen.Schedule.from_json(s.to_json())
        assert back == s
        # the sharing structure survives the wire exactly
        assert [(r.template, r.parent) for r in back.requests] \
            == [(r.template, r.parent) for r in s.requests]
        assert s.spec["kind"] == "shared_prefix"
        assert s.spec["burst_factor"] == 4.0

    def test_template_mix_follows_weights(self):
        s = _shared(n=512, seed=3, template_weights=(6.0, 1.0, 1.0))
        tmpl = [r.template for r in s.requests]
        assert all(t >= 0 and r.parent < 0
                   for t, r in zip(tmpl, s.requests))  # no tree turns
        # the hot template carries ~6/8 of traffic
        assert tmpl.count(0) / 512 == pytest.approx(0.75, abs=0.08)
        for r in s.requests:
            assert r.prompt_len - 16 in (3, 5, 8)
            assert r.max_new in (4, 8)

    def test_per_template_lengths(self):
        s = _shared(n=128, seed=4, template_len=(8, 16, 32))
        lens = (8, 16, 32)
        for r in s.requests:
            assert r.prompt_len - lens[r.template] in (3, 5, 8)

    def test_tree_turns_extend_earlier_prompts(self):
        s = _shared(n=256, seed=5, tree_frac=0.5)
        turns = [r for r in s.requests if r.parent >= 0]
        # ~half the stream is follow-up turns (the first never is)
        assert len(turns) / 256 == pytest.approx(0.5, abs=0.1)
        for r in turns:
            assert r.template == -1 and r.parent < r.index
            parent = s.requests[r.parent]
            assert r.prompt_len - parent.prompt_len in (3, 5, 8)

    def test_dispersion_rides_the_arrival_process(self):
        # shared-prefix structure reuses the named process untouched:
        # the bursty variant keeps its index-of-dispersion signature
        def dispersion(sched):
            t = np.array([r.t_arrival_s for r in sched.requests])
            counts, _ = np.histogram(t, bins=max(4, int(t[-1] / 0.1)))
            return counts.var() / max(counts.mean(), 1e-9)

        poisson = dispersion(_shared("poisson", n=512, seed=6))
        bursty = dispersion(_shared("bursty", n=512, seed=6,
                                    burst_factor=16.0))
        assert bursty > 2.0 * poisson

    def test_materialize_prompt_shares_prefix_bytes(self):
        s = _shared(n=64, seed=7, tree_frac=0.4)
        prompts = [loadgen.materialize_prompt(s, i, vocab=256)
                   for i in range(s.n)]
        again = [loadgen.materialize_prompt(s, i, vocab=256)
                 for i in range(s.n)]
        by_tmpl: dict[int, list[int]] = {}
        for i, r in enumerate(s.requests):
            assert len(prompts[i]) == r.prompt_len
            np.testing.assert_array_equal(prompts[i], again[i])
            if r.parent >= 0:
                # a tree turn extends its parent's prompt bit-exactly
                np.testing.assert_array_equal(
                    prompts[i][:len(prompts[r.parent])],
                    prompts[r.parent])
            else:
                by_tmpl.setdefault(r.template, []).append(i)
        for idxs in by_tmpl.values():
            first = prompts[idxs[0]][:16]
            for i in idxs[1:]:
                # same template -> the SAME 16 leading tokens...
                np.testing.assert_array_equal(prompts[i][:16], first)
        # ...and tails diverge between requests on one template
        hot = max(by_tmpl.values(), key=len)
        assert any(not np.array_equal(prompts[i], prompts[j])
                   for i in hot for j in hot if i != j)

    def test_guards(self):
        with pytest.raises(ValueError, match="n_templates"):
            _shared(n_templates=0)
        with pytest.raises(ValueError, match="tree_frac"):
            _shared(tree_frac=1.5)
        with pytest.raises(ValueError, match="template_len"):
            _shared(template_len=(8, 16))  # 2 lengths, 3 templates
        with pytest.raises(ValueError, match="template_weights"):
            _shared(template_weights=(1.0,))
        with pytest.raises(ValueError, match="unknown process"):
            _shared("weekly")


class TestStaged:
    def test_staged_schedule_is_literal(self):
        inter, batch = CLASSES
        s = loadgen.staged_schedule([
            (0.0, batch, 32, 160),
            (0.25, inter, 16, 16),
        ])
        assert s.n == 2 and s.spec["process"] == "staged"
        assert s.requests[1].t_arrival_s == 0.25
        assert s.requests[1].priority == 0
        assert loadgen.Schedule.from_json(s.to_json()) == s

    def test_staged_rejects_time_travel(self):
        inter, batch = CLASSES
        with pytest.raises(ValueError, match="non-decreasing"):
            loadgen.staged_schedule([(1.0, batch, 8, 4),
                                     (0.5, inter, 8, 4)])


class TestGuards:
    def test_bad_params_raise(self):
        with pytest.raises(ValueError, match="unknown process"):
            _sched("weekly")
        with pytest.raises(ValueError, match="rate_rps"):
            loadgen.make_schedule(4, rate_rps=0.0, classes=CLASSES,
                                  prompt_lens=(8,), budgets=(4,))
        with pytest.raises(ValueError, match="PriorityClass"):
            loadgen.make_schedule(4, rate_rps=1.0, classes=(),
                                  prompt_lens=(8,), budgets=(4,))
        with pytest.raises(ValueError, match="depth"):
            _sched("diurnal", depth=1.5)
        with pytest.raises(ValueError, match="burst_factor"):
            _sched("bursty", burst_factor=0.5)
