"""Mesh-spec helpers shared by the model and its sharding rules.

Separate from models/sharding.py (which depends on the model config) so
transformer.py can import these without a cycle.
"""

from __future__ import annotations

from jax.sharding import Mesh, PartitionSpec as P


def resolve_spec(spec: P, mesh: Mesh) -> P:
    """Drop spec axes the mesh doesn't have (→ replicated on that dim),
    so one rule table serves every mesh shape — a dp-only mesh simply
    replicates the tp/ep-sharded dims, the reference's fallback-to-
    whole-device philosophy (devices.hpp:33-38). Tuple entries (axis
    groups like ``(dp, ep)``) keep only their present members."""

    def fix(ax):
        if isinstance(ax, tuple):
            kept = tuple(a for a in ax if a in mesh.axis_names)
            return kept if len(kept) > 1 else (kept[0] if kept else None)
        return ax if ax is None or ax in mesh.axis_names else None

    return P(*(fix(ax) for ax in spec))


def mesh_axis_size(mesh: Mesh, name: str) -> int:
    """Axis size, 1 when the mesh doesn't carry the axis (pruned away)."""
    return mesh.shape[name] if name in mesh.axis_names else 1
