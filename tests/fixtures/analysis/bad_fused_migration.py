"""Known-bad: the round-17 device-side-migration bug shapes,
minimized. ``send_migration`` drags the payload through the host on
the dispatch path — the exact staging the DMA tier exists to delete,
stalling the destination's in-flight decode chunk behind a readback.
``exchange_shared_landing_slot`` lands two semaphore families in ONE
recv buffer: nothing orders the payload copy's completion against the
ack copy's write, so the ack can clobber bytes the installer is still
reading — the cross-family sibling of the PR 8 gather-slot race."""

import numpy as np

import jax
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu


def _remote(src, dst, send, recv, dev):
    return pltpu.make_async_remote_copy(
        src_ref=src, dst_ref=dst, send_sem=send, recv_sem=recv,
        device_id=dev, device_id_type=pltpu.DeviceIdType.LOGICAL)


def send_migration(bundle, dst_device):
    """Host-staged 'device-side' migration: the np.asarray readback
    synchronizes the source's queue and ships every page slab through
    host memory before re-uploading it — device_put with extra steps,
    on the one path that must stay dispatch-only."""
    staged = [np.asarray(page) for page in bundle]  # EXPECT: host-sync-in-dispatch
    return [jax.device_put(p, dst_device) for p in staged]


def exchange_shared_landing_slot(x, axis, size):
    """The migration pair with the ack riding the payload's landing
    buffer: chunk 0's page copy arrives in recvbuf under the payload
    semaphore family, then the ack DMA lands in the SAME buffer under
    its own family — the installer's read of the pages races the ack's
    write (dedicated per-purpose recv buffers are the discipline)."""

    def kernel(x_ref, o_ref, recvbuf, pay_send, pay_recv, ack_send,
               ack_sem):
        me = lax.axis_index(axis)
        dst = lax.rem(me + 1, size)
        d = _remote(x_ref, recvbuf.at[0], pay_send.at[0],
                    pay_recv.at[0], dst)
        d.start()
        d.wait()
        a = _remote(x_ref, recvbuf.at[1], ack_send.at[0],
                    ack_sem.at[0], dst)
        a.start()  # EXPECT: dma-slot-reuse
        a.wait()
        o_ref[...] = recvbuf[0]

    return pl.pallas_call(kernel, out_shape=x)(x)
