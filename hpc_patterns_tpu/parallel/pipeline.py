"""Pipeline parallelism: microbatch fill-drain over the pt2pt ring.

The reference's pairwise blocking Send/Recv between ring neighbors is
"the core of PP" (SURVEY.md §2.2): a pipeline stage boundary is exactly
one neighbor handoff per tick. This module turns that primitive
(comm.ring.ring_shift — deadlock-free ppermute, vs the reference's
even/odd ordering trick, allreduce-mpi-sycl.cpp:50-58) into a GPipe-style
forward schedule: rank r runs stage r; microbatch m enters at tick m,
reaches stage r at tick m+r, exits after M + P - 1 ticks.

SPMD subtlety: inside ``shard_map`` every rank executes the same program,
so "is my buffer valid at this tick" is data (a mask), not control flow —
inactive (fill/drain bubble) ticks compute on garbage and mask the
result, the standard XLA-friendly formulation (static tick loop, no
data-dependent branching — SURVEY.md's XLA-semantics ground rule).
"""

from __future__ import annotations

from typing import Callable

import jax.numpy as jnp

from hpc_patterns_tpu.comm import ring


def pipeline_forward(
    stage_fn: Callable,
    stage_params,
    x_microbatches,
    axis: str,
):
    """Run ``stage_fn(stage_params, x)`` as a P-stage pipeline over the
    mesh axis (rank-local; run inside ``shard_map``).

    ``stage_params``: this rank's stage parameters (stage r on rank r).
    ``x_microbatches``: (M, ...) microbatches — read on rank 0 (the
    pipeline entry); other ranks may pass zeros of the same shape.
    Returns (M, ...) outputs, valid on the LAST rank (rank size-1); other
    ranks return zeros — fetch the last-rank shard, or close the ring
    with one more hop if replication is wanted.
    """
    size = ring.axis_size(axis)
    me = ring.axis_index(axis)
    M = x_microbatches.shape[0]
    mb_shape = x_microbatches.shape[1:]

    buf = jnp.zeros(mb_shape, x_microbatches.dtype)  # incoming activation
    outs = jnp.zeros((M, *mb_shape), x_microbatches.dtype)

    for tick in range(M + size - 1):
        # entry rank injects microbatch `tick` during the fill window
        feed_idx = min(tick, M - 1)
        cur = jnp.where(me == 0, x_microbatches[feed_idx], buf)
        # stage r is active for microbatch (tick - r) in [0, M)
        active = jnp.logical_and(tick - me >= 0, tick - me < M)
        y = stage_fn(stage_params, cur)
        if y.shape != cur.shape or y.dtype != cur.dtype:
            # the handoff buffer is reused every tick, so stages must be
            # shape/dtype-preserving (project in/out inside stage_fn)
            raise ValueError(
                f"stage_fn must preserve microbatch shape/dtype: "
                f"{cur.shape}/{cur.dtype} -> {y.shape}/{y.dtype}"
            )
        y = jnp.where(active, y, jnp.zeros_like(y))
        # last stage banks its finished microbatch
        out_idx = max(min(tick - (size - 1), M - 1), 0)
        bank = jnp.logical_and(active, me == size - 1)
        outs = outs.at[out_idx].set(jnp.where(bank, y, outs[out_idx]))
        # neighbor handoff (the SendRecvRing hop); last->0 wraps but rank 0
        # overwrites with its injection, so the wrap is harmless
        buf = ring.ring_shift(y, axis, 1)

    return outs
