"""Flagship model family: a TPU-first decoder-only transformer.

The reference has no model zoo (SURVEY.md: "no scheduler daemon, no
model zoo, no training loop") — but its ring/pt2pt/collective patterns
are the building blocks of ML parallelism, and SURVEY.md §2.2 requires
them "API-shaped so these [TP/SP/ring-attention] can be layered on".
This package is the proof of that layering: a transformer whose

- tensor parallelism is the Megatron column/row sharding the
  :mod:`~hpc_patterns_tpu.parallel.tensor` helpers express,
- long-context path is :func:`~hpc_patterns_tpu.parallel.ring_attention`
  (the reference's ring dataflow generalized),
- data/sequence parallelism is pure ``jax.sharding`` annotation —
  XLA inserts the ICI collectives (the §2.3 "GPU-aware" property).

Design: pure-JAX pytree params (no framework layer), f32 master params
with bf16 (MXU-native) compute, layers stacked for ``lax.scan`` (one
compile per model, not per layer), optional ``jax.checkpoint`` remat.
"""

from hpc_patterns_tpu.models.transformer import (  # noqa: F401
    ATTENTION_IMPLS,
    TransformerConfig,
    init_params,
    forward,
    loss_fn,
)
from hpc_patterns_tpu.models.train import make_train_step, make_optimizer  # noqa: F401
from hpc_patterns_tpu.models.sharding import param_shardings, batch_sharding  # noqa: F401
from hpc_patterns_tpu.models.decode import (  # noqa: F401
    extend_step,
    generate,
    greedy_generate,
    init_cache,
    init_paged_cache,
    paged_generate,
    prefill,
)
from hpc_patterns_tpu.models.speculative import (  # noqa: F401
    speculative_generate,
    speculative_generate_batched,
)
from hpc_patterns_tpu.models.quantization import (  # noqa: F401
    precision_law,
    quantize_weights_int8,
)
