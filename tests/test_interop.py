"""Interop suite tests (C10): native bindings, zero-copy proofs, app."""

import numpy as np
import pytest

import jax.numpy as jnp

from hpc_patterns_tpu.interop import native, zero_copy

pytestmark = pytest.mark.skipif(
    not (native.available() or native.build()),
    reason="native library unavailable",
)


class TestNativeBindings:
    def test_stats_matches_numpy(self):
        xs = [3.0, 1.0, 2.0, 5.0]
        got = native.stats(xs)
        assert got["min"] == 1.0 and got["max"] == 5.0
        np.testing.assert_allclose(got["mean"], np.mean(xs))
        np.testing.assert_allclose(got["std"], np.std(xs))

    def test_roundtrip_identity(self):
        xs = [0.1, 0.2, 0.3]
        assert native.stats_roundtrip(xs) == xs

    @pytest.mark.parametrize("alignment", [128, 4096, 1 << 21])
    def test_aligned_alloc(self, alignment):
        buf = native.AlignedBuffer(100, alignment=alignment)
        assert buf.address % alignment == 0
        view = buf.as_numpy()
        assert view.shape == (100,) and view.dtype == np.float32

    def test_fill_iota_validate(self):
        buf = native.AlignedBuffer(64)
        buf.fill(7.0)
        assert buf.validate(7.0) == -1
        buf.as_numpy()[10] = 8.0
        assert buf.validate(7.0) == 10  # first bad index, like the
        # reference's elementwise loop (allreduce-mpi-sycl.cpp:192-204)
        buf.iota(0.0, 2.0)
        np.testing.assert_allclose(buf.as_numpy()[:4], [0, 2, 4, 6])

    def test_ring_plan_matches_python(self):
        from hpc_patterns_tpu.comm.ring import _ring_perm

        for size in (2, 4, 8):
            for shift in (1, -1, 3):
                assert native.ring_plan(size, shift) == _ring_perm(size, shift)

    def test_ring_phases_cover_all_ranks_once(self):
        even = native.ring_phase_senders(8, 0)
        odd = native.ring_phase_senders(8, 1)
        assert sorted(even + odd) == list(range(8))
        assert all(r % 2 == 0 for r in even) and all(r % 2 == 1 for r in odd)


class TestZeroCopy:
    def test_numpy_jax_roundtrip_pointer_identity(self):
        # XLA aliases only >=64B-aligned imports — use the native
        # allocator (the reason it exists; see zero_copy.numpy_to_jax)
        buf = native.AlignedBuffer(256, alignment=128)
        buf.iota(0.0, 1.0)
        x = buf.as_numpy()
        arr, zc = zero_copy.numpy_to_jax(x)
        assert zc, "aligned numpy->jax must alias on CPU"
        back, zc2 = zero_copy.jax_to_numpy(arr)
        assert zc2
        np.testing.assert_array_equal(back, x)

    def test_unaligned_numpy_falls_back_to_copy(self):
        x = np.arange(257, dtype=np.float32)[1:]  # force 4B-offset storage
        arr, zc = zero_copy.numpy_to_jax(x)
        assert not zc  # copied, values still right
        np.testing.assert_array_equal(np.asarray(arr), x)

    def test_jax_torch_bridge(self):
        torch = pytest.importorskip("torch")
        import jax

        arr = jax.device_put(
            jnp.arange(64, dtype=jnp.float32), jax.devices("cpu")[0]
        )
        arr = jax.block_until_ready(arr)
        t, zc = zero_copy.jax_to_torch(arr)
        assert zc and isinstance(t, torch.Tensor)
        back, zc2 = zero_copy.torch_to_jax(t)
        assert zc2
        np.testing.assert_array_equal(np.asarray(back), np.asarray(arr))

    def test_view_outlives_buffer(self):
        """Regression: views keep the C allocation alive (no
        use-after-free when the AlignedBuffer is dropped first)."""
        import gc

        view = native.AlignedBuffer(64).as_numpy()  # buffer unreferenced
        gc.collect()
        view[:] = 1.0  # would corrupt freed heap without the owner ref
        assert view.sum() == 64.0

    def test_native_to_jax_chain(self):
        buf = native.AlignedBuffer(128)
        buf.iota(1.0, 1.0)
        arr, zc = zero_copy.native_to_jax(buf)
        assert zc
        np.testing.assert_allclose(
            np.asarray(arr), np.arange(1, 129, dtype=np.float32)
        )


class TestDeviceAliasing:
    def test_donation_writes_in_place(self):
        from hpc_patterns_tpu.interop import device

        ok, ev = device.donation_alias_proof(4096)
        assert ok, ev
        # CPU backend exposes raw pointers: identity must be proven,
        # not just the compiled contract
        assert ev["pointer_ok"] is True
        assert ev["contract_ok"] and ev["input_invalidated"]

    def test_pallas_input_output_alias(self):
        from hpc_patterns_tpu.interop import device

        ok, ev = device.pallas_alias_proof()
        assert ok, ev
        assert ev["pointer_ok"] is True
        assert ev["alias_bytes"] == ev["output_bytes"] > 0


class TestInteropApp:
    def test_app_passes(self, capsys):
        from hpc_patterns_tpu.apps import interop_app

        try:
            import torch  # noqa: F401 — app skips its torch legs without it

            min_passed = 7
        except ImportError:
            min_passed = 5
        code = interop_app.main(["-n", "4096"])
        out = capsys.readouterr().out
        assert code == 0, out
        assert "SUCCESS" in out
        assert out.count("Passed") >= min_passed

    @pytest.mark.slow  # compiles + embeds CPython, runs XLA in-process
    def test_native_driver_leg(self, capsys):
        from hpc_patterns_tpu.apps import interop_app

        code = interop_app.main(["-n", "4096", "--native-driver"])
        out = capsys.readouterr().out
        assert code == 0, out
        assert "native C++ XLA driver" in out
        assert "[driver] SUCCESS" in out
