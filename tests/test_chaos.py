"""Chaos injectors (harness/chaos.py): spec parsing, deterministic
scheduling/jitter, the env/override precedence, and the live wiring
into the two hot-path sites — the serving loop's ``engine_round`` and
the eager Communicator's ``collective``. The launcher-level scenarios
(straggler named by the merged rollup, worker death in the rank
report) live in tests/test_launch.py; the serving-side preemption
composition in tests/test_serving.py."""

import time

import numpy as np
import pytest

from hpc_patterns_tpu.harness import chaos


@pytest.fixture(autouse=True)
def _clean_chaos(monkeypatch):
    monkeypatch.delenv(chaos.ENV_CHAOS, raising=False)
    chaos.reset()
    yield
    chaos.reset()


class TestParse:
    def test_straggler_spec(self):
        (f,) = chaos.parse("straggler:rank=1,delay_ms=40")
        assert f.kind == "straggler" and f.site == "collective"
        assert f.rank == 1 and f.delay_s == pytest.approx(0.04)
        assert f.every == 1  # stragglers recur by default

    def test_stall_and_die_fire_once_by_default(self):
        stall, die = chaos.parse("stall:at=3,delay_ms=100;die:rank=0,at=5")
        assert stall.site == "engine_round" and stall.every == 0
        assert stall.matches("engine_round", 3, 0)
        assert not stall.matches("engine_round", 4, 0)
        assert die.every == 0 and die.exit_code is None

    def test_slow_host_transfer_spec(self):
        # the round-11 tiered-memory injector: defaults to the
        # host_transfer site (the residency manager's prefetch
        # dispatch) and recurs like a straggler — degraded bandwidth
        # is a condition, not an event
        (f,) = chaos.parse("slow_host_transfer:delay_ms=40")
        assert f.kind == "slow_host_transfer"
        assert f.site == "host_transfer"
        assert f.delay_s == pytest.approx(0.04)
        assert f.every == 1
        assert f.matches("host_transfer", 0, 0)
        assert not f.matches("collective", 0, 0)
        (g,) = chaos.parse("slow_host_transfer:at=2,delay_ms=40,every=0")
        assert g.matches("host_transfer", 2, 0)
        assert not g.matches("host_transfer", 3, 0)

    def test_every_and_at_schedule(self):
        (f,) = chaos.parse("straggler:delay_ms=1,at=2,every=4")
        fired = [i for i in range(12) if f.matches("collective", i, 0)]
        assert fired == [2, 6, 10]

    def test_rank_filter(self):
        (f,) = chaos.parse("straggler:rank=1,delay_ms=1")
        assert f.matches("collective", 0, 1)
        assert not f.matches("collective", 0, 0)
        (g,) = chaos.parse("straggler:delay_ms=1")  # rank omitted = all
        assert g.matches("collective", 0, 0) and g.matches("collective", 0, 7)

    def test_bad_specs_raise(self):
        # a typo'd spec silently injecting nothing would fake a healthy
        # run out of a chaos scenario — every unknown token is an error
        with pytest.raises(ValueError, match="unknown chaos kind"):
            # jaxlint: disable=chaos-site-drift — the typo is the
            # test: parse() must reject it, which is the runtime half
            # of the contract the static rule checks
            chaos.parse("stragler:delay_ms=1")
        with pytest.raises(ValueError, match="unknown chaos key"):
            chaos.parse("straggler:delay=1")
        with pytest.raises(ValueError, match="unknown chaos site"):
            chaos.parse("straggler:site=nowhere")

    def test_deterministic_jitter(self):
        (f,) = chaos.parse("straggler:delay_ms=10,jitter_ms=10,seed=3")
        a = [f.delay_at("collective", i) for i in range(8)]
        b = [f.delay_at("collective", i) for i in range(8)]
        assert a == b  # pure hash: a replay is the same perturbation
        assert all(0.01 <= d <= 0.02 for d in a)
        assert len(set(a)) > 1  # and it IS jitter, not a constant
        (g,) = chaos.parse("straggler:delay_ms=10,jitter_ms=10,seed=4")
        assert [g.delay_at("collective", i) for i in range(8)] != a


class TestActivation:
    def test_env_spec_parsed_and_cached(self, monkeypatch):
        monkeypatch.setenv(chaos.ENV_CHAOS, "stall:at=1,delay_ms=5")
        (f,) = chaos.active()
        assert f.kind == "stall"
        assert chaos.active()[0] is f  # cached per env value

    def test_configure_overrides_env_and_none_disables(self, monkeypatch):
        monkeypatch.setenv(chaos.ENV_CHAOS, "stall:at=1,delay_ms=5")
        chaos.configure("straggler:delay_ms=1")
        assert chaos.active()[0].kind == "straggler"
        chaos.configure(None)  # explicitly OFF, env notwithstanding
        assert chaos.active() is None
        chaos.reset()
        assert chaos.active()[0].kind == "stall"

    def test_no_spec_means_no_chaos(self):
        assert chaos.active() is None
        chaos.maybe_inject("collective", 0)  # no-op, no log
        assert chaos.injections() == ()

    def test_process_id_env_is_the_rank(self, monkeypatch):
        # stays a literal in chaos.py so it imports jax-free; must
        # match topology's constant (same discipline as analysis/runtime)
        from hpc_patterns_tpu import topology

        assert chaos.ENV_PROCESS_ID == topology.ENV_PROCESS_ID
        chaos.configure("stall:rank=3,at=0,delay_ms=0")
        monkeypatch.setenv(chaos.ENV_PROCESS_ID, "3")
        chaos.maybe_inject("engine_round", 0)
        assert len(chaos.injections()) == 1
        monkeypatch.setenv(chaos.ENV_PROCESS_ID, "2")
        chaos.configure("stall:rank=3,at=0,delay_ms=0")
        chaos.maybe_inject("engine_round", 0)
        assert chaos.injections() == ()


class TestInjection:
    def test_straggler_sleeps_and_logs(self):
        chaos.configure("straggler:delay_ms=30,at=1")
        t0 = time.perf_counter()
        chaos.maybe_inject("collective", 0)  # below at: no delay
        assert time.perf_counter() - t0 < 0.02
        t0 = time.perf_counter()
        chaos.maybe_inject("collective", 1)
        assert time.perf_counter() - t0 >= 0.03
        log = chaos.injections()
        assert [e["index"] for e in log] == [1]
        assert log[0]["delay_s"] == pytest.approx(0.03)

    def test_engine_round_site_stalls_the_serving_loop(self):
        # the REAL wiring: ContinuousBatcher.run probes engine_round
        # once per scheduler round, so a seeded stall pauses the loop
        import jax

        from hpc_patterns_tpu.models import TransformerConfig, init_params
        from hpc_patterns_tpu.models.serving import ContinuousBatcher

        cfg = TransformerConfig(vocab=64, d_model=32, n_heads=4,
                                n_layers=2, d_ff=64, max_seq=64,
                                dtype="float32")
        params = init_params(jax.random.PRNGKey(0), cfg)

        def serve():
            eng = ContinuousBatcher(params, cfg, slots=1, pool_pages=3,
                                    pages_per_seq=3, page_size=8,
                                    chunk=2)
            sid = eng.submit(np.arange(5, dtype=np.int32), 8)
            t0 = time.perf_counter()
            got = eng.run()[sid]
            return got, time.perf_counter() - t0

        clean, _t_clean = serve()
        chaos.configure("stall:at=1,delay_ms=120")
        stalled, t_stalled = serve()
        hits = [e for e in chaos.injections()
                if e["site"] == "engine_round"]
        assert len(hits) == 1 and hits[0]["index"] == 1
        # race-free floor: the run CONTAINS the 120ms sleep, so its
        # wall clock cannot undercut it (comparing against a one-shot
        # clean baseline was load-flaky)
        assert t_stalled >= 0.12
        # a stalled host is a LATENCY fault, not a correctness one
        np.testing.assert_array_equal(stalled, clean)

    def test_collective_site_delays_timed_reps(self):
        # the other half of the straggler wiring: harness.timing.measure
        # probes the collective site per timed rep (the rep IS the
        # launched benchmarks' collective loop — PR 5's skew-fan
        # identification), on the disabled fast path too
        from hpc_patterns_tpu.harness import timing

        chaos.configure("straggler:delay_ms=30,at=1")
        t0 = time.perf_counter()
        r = timing.measure(lambda: None, repetitions=3, warmup=0)
        elapsed = time.perf_counter() - t0
        assert len(r.times_s) == 3
        hits = [e["index"] for e in chaos.injections()
                if e["site"] == "collective"]
        assert hits == [1, 2]
        assert elapsed >= 0.06
        # the delay lands BEFORE each rep's clock starts (a late START,
        # the straggler shape) — the rep times themselves stay honest
        assert max(r.times_s) < 0.03

    def test_timed_rep_claims_the_collective_site(self):
        # an eager collective INSIDE a timed rep must not re-inject
        # the fault the rep already injected — the rep IS the
        # collective in the skew-fan identification, and a double
        # delay would misstate the declared spec
        from hpc_patterns_tpu.harness import timing

        chaos.configure("straggler:delay_ms=0")

        def fn():
            chaos.maybe_inject("collective", 99)  # the inner probe

        timing.measure(fn, repetitions=2, warmup=0)
        assert [e["index"] for e in chaos.injections()] == [0, 1]
        # outside a rep the inner probe fires normally
        fn()
        assert [e["index"] for e in chaos.injections()] == [0, 1, 99]

    def test_collective_site_delays_the_communicator_hot_path(self):
        # the straggler wiring: the eager Communicator probes the
        # collective site per collective (seq-indexed), so the injected
        # delay lands inside the measured issue path
        import jax
        from jax.sharding import Mesh

        from hpc_patterns_tpu.comm.communicator import Communicator

        mesh = Mesh(np.array(jax.devices()[:2]), ("x",))
        comm = Communicator(mesh, "x")
        x = comm.rank_filled(8)
        comm.allreduce(x)  # seq 0: warm the compile un-delayed
        chaos.configure("straggler:delay_ms=40,at=1")
        t0 = time.perf_counter()
        out = comm.allreduce(x)  # seq 1
        assert time.perf_counter() - t0 >= 0.04
        hits = [e for e in chaos.injections()
                if e["site"] == "collective"]
        assert [e["index"] for e in hits] == [1]
        np.testing.assert_allclose(
            np.asarray(out)[0], comm.expected_allreduce_value())


class TestReplicaSite:
    """Round 10: replica-level chaos — ``replica_round`` is the
    serving plane's per-replica scheduler-round site and ``replica=``
    aliases ``rank=`` (one launched replica IS one launcher process).
    The end-to-end drill (die kills one replica of three, the router
    resumes its work on survivors) lives in
    tests/test_launch.py::TestServingPlaneLaunch."""

    def test_replica_key_aliases_rank(self):
        (f,) = chaos.parse("die:replica=2,at=5,site=replica_round")
        assert f.kind == "die" and f.site == "replica_round"
        assert f.rank == 2 and f.at == 5
        assert f.every == 0  # death still fires once definitionally

    def test_replica_round_site_matches_only_itself(self):
        (f,) = chaos.parse(
            "stall:replica=1,at=2,site=replica_round,delay_ms=5")
        assert f.matches("replica_round", 2, 1)
        assert not f.matches("engine_round", 2, 1)
        assert not f.matches("replica_round", 2, 0)

    def test_stub_replica_round_probe_fires(self, monkeypatch):
        # the plane's stub replica probes the site once per protocol
        # round — the same probe the real adapter makes
        from hpc_patterns_tpu.serving_plane.service import StubAdapter

        chaos.configure("stall:at=1,delay_ms=30,site=replica_round")
        adapter = StubAdapter(slots=1, pool_pages=4, pages_per_seq=4,
                              page_size=8, chunk=2)
        t0 = time.perf_counter()
        adapter.round(None)
        adapter.round(None)  # index 1: the stall fires here
        dt = time.perf_counter() - t0
        fired = [e for e in chaos.injections()
                 if e["site"] == "replica_round"]
        assert len(fired) == 1 and fired[0]["index"] == 1
        assert dt >= 0.03


class TestMatching:
    """Round 14: ``matching``/``record_injection`` — the caller-
    executed injection pair the IN-PROCESS serving plane uses (every
    replica shares one OS process, so a die fault must mark ONE
    replica dead instead of SIGKILLing the plane; the plane executes
    the semantics, these helpers keep the determinism and the
    fault-actually-fired log)."""

    def test_matching_returns_without_executing(self):
        chaos.configure("die:replica=1,at=2,site=replica_round")
        # a die fault MATCHED but not executed: the process survives
        assert chaos.matching("replica_round", 2, rank=1)
        assert not chaos.matching("replica_round", 2, rank=0)
        assert not chaos.matching("replica_round", 1, rank=1)
        assert not chaos.matching("engine_round", 2, rank=1)
        assert chaos.injections() == ()  # nothing logged either

    def test_rank_overrides_process_rank(self, monkeypatch):
        # the explicit rank is the REPLICA ordinal, independent of
        # the process's own id
        monkeypatch.setenv(chaos.ENV_PROCESS_ID, "7")
        chaos.configure("stall:replica=3,at=0,delay_ms=1,"
                        "site=replica_round")
        assert chaos.matching("replica_round", 0, rank=3)
        assert not chaos.matching("replica_round", 0)  # process rank 7

    def test_record_injection_feeds_the_log(self):
        chaos.configure("die:replica=1,at=0,site=replica_round")
        chaos.record_injection("replica_round", 0, "die", rank=1)
        (e,) = chaos.injections()
        assert e == {"site": "replica_round", "index": 0,
                     "kind": "die", "rank": 1, "delay_s": 0.0}

    def test_matching_respects_suppression_and_off(self):
        chaos.configure("stall:at=0,delay_ms=1,site=replica_round")
        with chaos.suppress("replica_round"):
            assert chaos.matching("replica_round", 0, rank=0) == ()
        chaos.configure(None)
        assert chaos.matching("replica_round", 0, rank=0) == ()
