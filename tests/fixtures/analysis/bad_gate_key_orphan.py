"""Known-bad: orphaned gate keys and string-consumed metric names —
the minimized replica of "a gated key whose emitter was deleted". The
gate table still lists ``detail.engine_bubble_frac``, but the bench
detail dict below stopped emitting it (the PR 5 runtime coverage-loss
warning fired one bench run too late; contractlint flags the
surviving consumer row at review time). Same shape for a metric name
read by string with no gauge producer, and a device-window span name
nothing dispatches."""


class MetricSpec:
    def __init__(self, path, direction, gated=True, abs_slack=0.0):
        self.path, self.direction = path, direction
        self.gated, self.abs_slack = gated, abs_slack


SPECS = (
    MetricSpec("value", "higher"),
    MetricSpec("detail.engine_tok_s", "higher"),
    # the emitter below used to write this key; it was deleted in a
    # "cleanup" and the gate row survived
    MetricSpec("detail.engine_bubble_frac", "lower"),  # EXPECT: gate-key-orphan
)


def bench_detail(engine_result):
    """The bench child's detail dict — engine_bubble_frac is gone."""
    return {
        "value": engine_result["speedup"],
        "engine_tok_s": round(engine_result["tok_s"], 1),
    }


def fit_engine(gauges, records):
    """An autofit-style consumer reading metric names by string."""
    # the gauge was renamed to engine.tok_s; this read kept the old name
    tok_s = gauges.get("engine.tokens_per_s")  # EXPECT: gate-key-orphan
    chunks = _windows(records, "engine.chunk")  # EXPECT: gate-key-orphan
    return tok_s, chunks


def _windows(records, name):
    return [r for r in records if r[0] == name]


def emit(metrics, engine_result):
    metrics.gauge("engine.tok_s", engine_result["tok_s"])
