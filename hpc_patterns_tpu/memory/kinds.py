"""Memory-kind probes and shardings — THE single source of truth.

Before round 11 three modules each carried their own copy of this
knowledge: ``concurrency/commands.py`` probed whether host<->device
memory-kind transfers actually execute (``_memory_kind_transfers_work``
/ ``_kind_sharding``), ``models/train.py`` retargeted tree shardings to
a kind (``memory_kind_shardings``), and ``apps/common.py`` answered the
advertise-level question (``supports_memory_kind``). Three copies of
"does this backend really have a host tier?" is how the
``offload_opt_state`` gap happened (an unsupported backend paid the
``device_put`` for no benefit) — so the helpers live HERE and the old
call sites delegate.

Three distinct questions, three probes — backends genuinely differ at
each level (this container's CPU exposes ``unpinned_host`` only; other
XLA:CPU builds advertise ``pinned_host`` yet reject the jitted
transfer at runtime):

- :func:`supports_memory_kind` — is the kind ADVERTISED in
  ``addressable_memories()``? (cheap; placement may still fail)
- :func:`memory_kind_placement_works` — does ``jax.device_put`` into
  the kind actually succeed? (what :func:`~hpc_patterns_tpu.models.
  train.offload_opt_state` needs)
- :func:`memory_kind_transfers_work` — does the full jitted
  host<->device round trip execute? (what the concurrency copy
  commands and the residency manager's pinned-host tier need)

Each probe runs once per (platform, kind) and is memoized; the probe
executes the SAME cached transfer program (:func:`move_to_kind`) the
real transfer paths dispatch, so it proves the executable that ships.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def kind_sharding(device, kind: str):
    """Single-device sharding targeting a JAX memory kind — the
    allocator axis as a placement (SURVEY.md §2, ``-H/-D``)."""
    return jax.sharding.SingleDeviceSharding(device, memory_kind=kind)


def memory_kind_shardings(tree, kind: str):
    """Shardings of ``tree``'s (concrete) leaves retargeted to a JAX
    memory kind — the L2 allocator axis applied to a whole state tree
    (training opt state, a gathered KV payload)."""
    return jax.tree.map(lambda x: x.sharding.with_memory_kind(kind), tree)


_MOVE_CACHE: dict[tuple, object] = {}


def move_to_kind(device, kind: str):
    """Cached jitted transfer program targeting ``kind`` on ``device``
    — every copy of the same direction shares one compile (the
    concurrency autotuner alone builds several probe commands per
    run, and the residency manager moves many blocks per round)."""
    key = (device, kind)
    if key not in _MOVE_CACHE:
        _MOVE_CACHE[key] = jax.jit(
            lambda x: x, out_shardings=kind_sharding(device, kind)
        )
    return _MOVE_CACHE[key]


def supports_memory_kind(kind: str, device=None) -> bool:
    """Whether the backend ADVERTISES the given memory kind (TPU has
    pinned_host + device; CPU meshes typically only the default).
    Advertise-level only — placement can still fail; see
    :func:`memory_kind_placement_works`."""
    try:
        device = device if device is not None else jax.devices()[0]
        memories = device.addressable_memories()
    except Exception:
        return False
    return any(m.kind == kind for m in memories)


_PLACEMENT_PROBE: dict[tuple[str, str], bool] = {}


def memory_kind_placement_works(device=None,
                                kind: str = "pinned_host") -> bool:
    """Whether ``jax.device_put`` INTO ``kind`` succeeds on this
    backend — the gate for one-way offloads (``offload_opt_state``):
    a backend that rejects the placement must return the input
    unchanged instead of paying a doomed transfer. Memoized per
    (platform, kind)."""
    device = device if device is not None else jax.devices()[0]
    key = (device.platform, kind)
    if key not in _PLACEMENT_PROBE:
        try:
            if not supports_memory_kind(kind, device):
                raise ValueError(f"no {kind} memory")
            tiny = jax.device_put(jnp.zeros((8,), jnp.float32),
                                  kind_sharding(device, kind))
            jax.block_until_ready(tiny)
            _PLACEMENT_PROBE[key] = True
        except Exception:
            _PLACEMENT_PROBE[key] = False
    return _PLACEMENT_PROBE[key]


_TRANSFER_PROBE: dict[str, bool] = {}


def memory_kind_transfers_work(device=None) -> bool:
    """Whether host<->device memory-kind transfers actually *execute*
    on this backend. Backends can advertise ``pinned_host`` in
    ``addressable_memories`` yet reject placement or the jitted
    transfer at runtime (XLA:CPU builds have done both), so probe by
    running one tiny round trip, memoized per platform. The probe
    executes the SAME cached transfer program real copy commands and
    residency-manager pulls use (a fresh ``jax.jit`` here would
    re-trace on every probe — jaxlint: recompile-hazard — and prove a
    different executable than the one that ships)."""
    device = device if device is not None else jax.devices()[0]
    key = device.platform
    if key not in _TRANSFER_PROBE:
        try:
            if not supports_memory_kind("pinned_host", device):
                raise ValueError("no pinned_host memory")
            tiny = jax.device_put(jnp.zeros((8,), jnp.float32),
                                  kind_sharding(device, "pinned_host"))
            moved = move_to_kind(device, "device")(tiny)
            jax.block_until_ready(moved)
            _TRANSFER_PROBE[key] = True
        except Exception:
            _TRANSFER_PROBE[key] = False
    return _TRANSFER_PROBE[key]
