"""jaxlint CLI: ``python -m hpc_patterns_tpu.analysis [paths] [--ci]``.

With no paths, analyzes the installed ``hpc_patterns_tpu`` package —
the tree CI gates on. ``--ci`` exits 1 on any unsuppressed,
unbaselined finding (0 on a clean tree), so the tier-1 suite and
``benchmarks/reground_r5.sh`` can both gate on it; the default mode
always exits 0 and just reports.

``--log FILE`` appends the verdict as a ``kind=analysis`` RunLog
record (rule counts, suppression count) to a JSONL log, where
``python -m hpc_patterns_tpu.harness.report`` surfaces it next to the
metrics and trace rollups.
"""

from __future__ import annotations

import argparse
import sys
from pathlib import Path

from hpc_patterns_tpu.analysis.core import (
    AnalysisConfig,
    load_baseline,
    registered_rules,
    run_paths,
    write_baseline,
)

_PACKAGE_ROOT = Path(__file__).resolve().parents[1]


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="python -m hpc_patterns_tpu.analysis",
        description=__doc__.splitlines()[0],
    )
    p.add_argument(
        "paths", nargs="*",
        help=f"files/directories to analyze (default: {_PACKAGE_ROOT})")
    p.add_argument(
        "--ci", action="store_true",
        help="exit 1 on any unsuppressed finding (the gate mode)")
    p.add_argument(
        "--select", action="append", metavar="RULE",
        help="run only these rules (repeatable)")
    p.add_argument(
        "--baseline", metavar="FILE",
        help="tolerate findings recorded in this baseline JSON")
    p.add_argument(
        "--write-baseline", metavar="FILE",
        help="write current findings as a baseline and exit 0 "
             "(adoption escape hatch; repo policy is fix-or-suppress)")
    p.add_argument(
        "--log", metavar="FILE",
        help="append the verdict as a kind=analysis RunLog record")
    p.add_argument(
        "--list-rules", action="store_true",
        help="print the rule catalog and exit")
    p.add_argument(
        "--vmem-report", action="store_true",
        help="print the per-kernel VMEM budget table (every "
             "pallas_call, model-dim bindings; analysis/vmem.py)")
    p.add_argument(
        "--contract-report", action="store_true",
        help="print the whole-tree producer/consumer tables the "
             "contractlint rules judge (gate keys, metric names, "
             "record kinds, track bands, chaos names; "
             "analysis/contracts.py)")
    return p


def main(argv=None) -> int:
    args = build_parser().parse_args(argv)
    if args.list_rules:
        # grouped by family in pipeline order: Python-level hazards,
        # SPMD hazards, in-kernel hazards, cross-module contracts
        rules = registered_rules()
        families = ["jaxlint", "shardlint", "pallaslint",
                    "contractlint"]
        families += sorted({r.family for r in rules.values()}
                           - set(families))
        for family in families:
            members = sorted((name, rule) for name, rule
                             in rules.items() if rule.family == family)
            if not members:
                continue
            print(f"{family}:")
            for name, rule in members:
                print(f"  {name:<26} {rule.summary}")
        return 0
    paths = args.paths or [_PACKAGE_ROOT]
    if args.select:
        # a typo'd --select would run ZERO rules and read as a clean
        # tree — the same strictness as unknown rules in suppressions
        unknown = sorted(set(args.select) - set(registered_rules()))
        if unknown:
            print(f"ERROR: unknown rule(s) in --select: "
                  f"{', '.join(unknown)}; registered: "
                  f"{', '.join(sorted(registered_rules()))}",
                  file=sys.stderr)
            return 2
    config = AnalysisConfig(
        select=frozenset(args.select) if args.select else None)
    baseline = None
    if args.baseline:
        try:
            baseline = load_baseline(args.baseline)
        except (OSError, ValueError) as e:
            print(f"ERROR: unreadable baseline {args.baseline}: {e}",
                  file=sys.stderr)
            return 2
    try:
        report = run_paths(paths, config, baseline)
    except OSError as e:
        print(f"ERROR: {e}", file=sys.stderr)
        return 2
    if report.n_files == 0:
        print("ERROR: no Python files under "
              + ", ".join(map(str, paths)), file=sys.stderr)
        return 2
    if args.write_baseline:
        write_baseline(args.write_baseline, report.findings)
        print(f"jaxlint: baselined {len(report.findings)} finding(s) "
              f"-> {args.write_baseline}")
        return 0
    vmem_stats = None
    if args.vmem_report or args.log:
        # the estimator is cheap (pure ast); computing it whenever a
        # log is written keeps the kind=analysis record's vmem section
        # present without a second invocation
        from hpc_patterns_tpu.analysis import vmem

        estimates = vmem.estimate_paths(paths)
        vmem_stats = vmem.vmem_summary(estimates)
        if args.vmem_report:
            print(vmem.format_vmem_table(estimates, root=_PACKAGE_ROOT))
    if args.contract_report:
        # the informational twin of --vmem-report: the full
        # producer/consumer tables the contractlint rules judged
        from hpc_patterns_tpu.analysis import contracts

        print(contracts.format_contract_report(
            contracts.tables_for_paths(paths)))
    for f in report.findings:
        print(f.format())
    counts = report.by_rule()
    by_rule = ", ".join(f"{k}={v}" for k, v in sorted(counts.items()))
    print(
        f"jaxlint: {len(report.findings)} finding(s)"
        + (f" [{by_rule}]" if counts else "")
        + f", {len(report.suppressed)} suppressed"
        + (f", {len(report.baselined)} baselined"
           if report.baselined else "")
        + f" across {report.n_files} file(s)"
    )
    if args.log:
        # local import: the RunLog record is the only jax-adjacent
        # dependency; the analyzer itself stays stdlib-only
        from hpc_patterns_tpu.harness.runlog import RunLog

        log = RunLog(args.log, truncate=False)
        log.emit(
            kind="analysis",
            ok=report.ok,
            findings=len(report.findings),
            suppressed=len(report.suppressed),
            baselined=len(report.baselined),
            files=report.n_files,
            by_rule=counts,
            vmem=vmem_stats,
        )
    if args.ci and report.findings:
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
