"""Tiered-memory subsystem (hpc_patterns_tpu/memory/): the hoisted
memory-kind probes, the residency manager's accounting + policies, and
the residency-managed training step.

The load-bearing claims: (1) there is ONE probe/sharding home —
concurrency/commands.py, models/train.py, and apps/common.py all
delegate here, so "does this backend have a host tier?" has one
memoized answer per process; (2) ``offload_opt_state`` on a backend
without a usable pinned_host tier returns the input UNCHANGED with a
note instead of paying a doomed transfer; (3) the residency-managed
streamed train step (pull dispatched before the gradient phase)
computes the SAME numbers as the fused single-jit step while the
manager measures the transfer windows it dispatched.
"""

import numpy as np
import pytest

import jax

from hpc_patterns_tpu.harness import metrics as metricslib
from hpc_patterns_tpu.harness import trace as tracelib
from hpc_patterns_tpu.memory import (
    ColdAfterNPolicy,
    LRUPolicy,
    PriorityAwarePolicy,
    ResidencyManager,
)
from hpc_patterns_tpu.memory import kinds as kindslib
from hpc_patterns_tpu.memory.residency import GroupView


class TestKindsDelegation:
    def test_commands_delegate_to_kinds(self):
        from hpc_patterns_tpu.concurrency import commands

        assert commands._kind_sharding is kindslib.kind_sharding
        assert (commands._memory_kind_transfers_work
                is kindslib.memory_kind_transfers_work)
        assert commands._move_to_kind is kindslib.move_to_kind

    def test_common_delegates_to_kinds(self):
        from hpc_patterns_tpu.apps import common

        # same answer, one probe home
        assert (common.supports_memory_kind("pinned_host")
                == kindslib.supports_memory_kind("pinned_host"))

    def test_train_delegates_to_kinds(self):
        from hpc_patterns_tpu.models.train import memory_kind_shardings

        x = jax.numpy.zeros((4,), jax.numpy.float32)
        tree = {"a": x, "b": (x, x)}
        kind = x.sharding.memory_kind or "unpinned_host"
        sh = memory_kind_shardings(tree, kind)
        assert jax.tree.structure(sh) == jax.tree.structure(tree)
        assert all(s.memory_kind == kind for s in jax.tree.leaves(sh))

    def test_move_to_kind_is_cached_per_direction(self):
        dev = jax.devices()[0]
        kind = {m.kind for m in dev.addressable_memories()}.pop()
        assert (kindslib.move_to_kind(dev, kind)
                is kindslib.move_to_kind(dev, kind))

    def test_probes_are_memoized_and_never_raise(self):
        dev = jax.devices()[0]
        a = kindslib.memory_kind_placement_works(dev)
        assert a == kindslib.memory_kind_placement_works(dev)
        b = kindslib.memory_kind_transfers_work(dev)
        assert b == kindslib.memory_kind_transfers_work(dev)
        assert isinstance(a, bool) and isinstance(b, bool)
        assert kindslib.supports_memory_kind("no-such-kind") is False


def _gv(group, n=4, tier="hbm", pinned=False, priority=0, touch=0,
        since=0):
    return GroupView(group=group, n_blocks=n, nbytes=n * 100,
                     tier=tier, pinned=pinned, priority=priority,
                     last_touch=touch, resident_since=since)


class TestPolicies:
    def test_lru_orders_by_touch_then_residency(self):
        groups = [_gv("a", touch=5, since=1), _gv("b", touch=3, since=2),
                  _gv("c", touch=3, since=0)]
        order = [g.group for g in LRUPolicy().victim_order(groups, 9)]
        assert order == ["c", "b", "a"]

    def test_priority_aware_pages_background_first(self):
        groups = [_gv("urgent", priority=0, touch=0),
                  _gv("batch", priority=2, touch=9),
                  _gv("mid", priority=1, touch=0)]
        order = [g.group
                 for g in PriorityAwarePolicy().victim_order(groups, 9)]
        assert order == ["batch", "mid", "urgent"]

    def test_cold_after_n_is_deterministic(self):
        pol = ColdAfterNPolicy(3)
        fresh = _gv("fresh", touch=8, since=8)
        cold = _gv("cold", touch=5, since=5)
        assert not pol.is_cold(fresh, 10)
        assert pol.is_cold(cold, 8)
        assert not pol.is_cold(cold, 7)
        with pytest.raises(ValueError):
            ColdAfterNPolicy(0)


class TestManagerAccounting:
    def test_register_retier_release_counts(self):
        m = ResidencyManager(host_blocks=8)
        m.register_group("r0", 4, 400)
        m.register_group("r1", 2, 200)
        assert m.hbm_blocks_used() == 6 and m.host_blocks_used() == 0
        m.retier_group("r0", "host")
        assert m.hbm_blocks_used() == 2 and m.host_blocks_used() == 4
        m.retier_group("r0", "hbm")
        assert m.host_blocks_used() == 0
        m.release_group("r0")
        m.release_group("r1")
        assert not m.blocks

    def test_duplicate_group_and_host_capacity_refused(self):
        m = ResidencyManager(host_blocks=4)
        m.register_group("r0", 3, 300)
        with pytest.raises(ValueError, match="already registered"):
            m.register_group("r0", 1, 100)
        m.register_group("r1", 3, 300)
        m.retier_group("r0", "host")
        with pytest.raises(ValueError, match="host tier full"):
            m.retier_group("r1", "host")
        assert not m.can_host(2)

    def test_victims_respect_pin_floor_and_priority(self):
        m = ResidencyManager(host_blocks=16, policy=LRUPolicy(),
                             min_resident_rounds=1)
        m.register_group("a", 4, 400, priority=1)
        m.register_group("b", 4, 400, priority=0)
        # round 0: everything inside the min-residency floor
        assert m.victims(4) == []
        m.begin_round()
        m.pin_group("a")
        # pinned "a" is never offered; "b" covers the need
        assert m.victims(4) == ["b"]
        m.pin_group("a", pinned=False)
        # min_priority: only strictly-less-urgent groups (>= 1)
        assert m.victims(4, min_priority=1) == ["a"]
        assert m.victims(4, min_priority=2) == []
        # exclusion composes
        assert m.victims(8, exclude=("a",)) == ["b"]

    def test_cold_groups_follow_policy(self):
        m = ResidencyManager(host_blocks=16,
                             policy=ColdAfterNPolicy(2))
        m.register_group("a", 4, 400)
        m.begin_round()
        assert m.cold_groups() == []
        m.begin_round()
        assert m.cold_groups() == ["a"]
        m.touch_group("a")
        # a touch alone does not reset residency age for decode rows;
        # cold-after-n keys on residency age too
        assert m.cold_groups() == ["a"]

    def test_gauges_land_in_registry(self):
        metricslib.configure(enabled=True)
        try:
            m = ResidencyManager(host_blocks=8)
            m.register_group("r0", 4, 400)
            m.retier_group("r0", "host")
            reg = metricslib.get_metrics()
            assert reg.gauge("mem.hbm_pages").last == 0
            assert reg.gauge("mem.host_pages").last == 4
        finally:
            metricslib.configure(enabled=False)


class TestTransfers:
    def test_overlap_not_inflated_by_late_completion(self):
        # a pull that completes long AFTER the consumer's compute
        # window ended must read as mostly UNHIDDEN — the honesty
        # property the train step's window-end choice relies on
        import time

        m = ResidencyManager(host_blocks=8)
        payload = {"k": (np.zeros((4,), np.float32),)}
        dev, handle = m.pull_payload(payload)
        jax.block_until_ready(dev)
        t0 = handle[3]
        time.sleep(0.05)
        m.complete_pull(handle, chunk_windows=((t0, t0 + 0.001),))
        assert m.prefetch_overlap_frac < 0.5

    def test_pull_and_push_roundtrip_and_windows(self):
        rec = tracelib.configure(enabled=True)
        try:
            m = ResidencyManager(host_blocks=8)
            payload = {"k": (np.arange(8, dtype=np.float32),)}
            dev, handle = m.pull_payload(payload)
            jax.block_until_ready(dev)
            m.complete_pull(handle, chunk_windows=())
            host = m.push_payload(dev)
            m.drain()
            np.testing.assert_array_equal(
                np.asarray(jax.device_get(host["k"][0])),
                payload["k"][0])
            names = [ev[2] for ev in rec.events
                     if ev[0] == "X" and ev[1] == "device"]
            assert "mem.prefetch" in names and "mem.evict" in names
            assert m.prefetch_bytes == 32 and m.swap_ins == 1
            assert m.prefetch_overlap_frac is not None
        finally:
            tracelib.configure(enabled=False)
            metricslib.configure(enabled=False)


TINY = dict(vocab=64, d_model=32, n_heads=2, n_layers=1, d_ff=64,
            max_seq=16, dtype="float32")


class TestTrainOffload:
    def _state(self):
        from hpc_patterns_tpu.models import TransformerConfig
        from hpc_patterns_tpu.models.train import (
            init_train_state,
            make_optimizer,
        )

        cfg = TransformerConfig(**TINY)
        opt = make_optimizer()
        params, st = init_train_state(jax.random.PRNGKey(0), cfg,
                                      optimizer=opt)
        return cfg, opt, params, st

    def test_offload_unsupported_backend_returns_input_unchanged(
            self, monkeypatch, capsys):
        # the round-11 gap fix: no usable pinned_host -> identity + a
        # note, instead of paying (or dying on) a doomed device_put
        from hpc_patterns_tpu.models.train import offload_opt_state

        monkeypatch.setattr(kindslib, "memory_kind_placement_works",
                            lambda device=None, kind="pinned_host":
                            False)
        _, _, _, st = self._state()
        hosted = offload_opt_state(st)
        assert hosted is st
        assert "no usable 'pinned_host'" in capsys.readouterr().out

    def test_offload_supported_backend_moves_state(self, monkeypatch):
        # with the probe green the old behavior is untouched: every
        # leaf retargets to the host kind (placement asserted via the
        # device_put call seam, so the test runs on any backend)
        from hpc_patterns_tpu.models import train as trainlib

        monkeypatch.setattr(kindslib, "memory_kind_placement_works",
                            lambda device=None, kind="pinned_host":
                            True)
        seen = {}

        def fake_put(tree, shardings):
            seen["kinds"] = {s.memory_kind
                             for s in jax.tree.leaves(shardings)}
            return tree

        monkeypatch.setattr(trainlib.jax, "device_put", fake_put)
        monkeypatch.setattr(
            trainlib, "memory_kind_shardings",
            lambda tree, kind: jax.tree.map(
                lambda x: type("S", (), {"memory_kind": kind})(), tree))
        _, _, _, st = self._state()
        trainlib.offload_opt_state(st)
        assert seen["kinds"] == {"pinned_host"}

    def test_streamed_step_matches_single_jit_step(self):
        from hpc_patterns_tpu.models.train import (
            make_batch,
            make_train_step,
        )

        cfg, opt, params, st = self._state()
        tokens = make_batch(jax.random.PRNGKey(1), cfg, 4, 16)
        step = make_train_step(cfg, optimizer=opt, accum_steps=2)
        l1, p1, _ = step(params, st, tokens)

        cfg2, opt2, params2, st2 = self._state()
        mgr = ResidencyManager(host_blocks=64)
        sstep = make_train_step(cfg2, optimizer=opt2, accum_steps=2,
                                offload_opt_example=st2, residency=mgr)
        l2, p2, s2 = sstep(params2, st2, tokens)
        np.testing.assert_allclose(float(l1), float(l2), rtol=1e-6)
        np.testing.assert_allclose(
            np.asarray(jax.device_get(p1["layers"]["wqkv"])),
            np.asarray(jax.device_get(p2["layers"]["wqkv"])),
            atol=1e-6)
        # the manager really moved the state and measured the pull
        assert mgr.swap_ins == 1 and mgr.swap_outs == 1
        assert mgr.prefetch_bytes > 0
        assert 0.0 <= (mgr.prefetch_overlap_frac or 0.0) <= 1.0
        # the pushed-back state feeds the next step (the loop contract)
        l3, _, _ = sstep(p2, s2, tokens)
        assert np.isfinite(float(l3))
        mgr.drain()

    def test_streamed_step_requires_offload_example(self):
        from hpc_patterns_tpu.models import TransformerConfig
        from hpc_patterns_tpu.models.train import make_train_step

        with pytest.raises(ValueError, match="offload_opt_example"):
            make_train_step(TransformerConfig(**TINY),
                            residency=ResidencyManager(host_blocks=4))
