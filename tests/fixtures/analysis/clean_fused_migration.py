"""Known-clean: the migration pair's shipped discipline
(``comm/migration_dma.py``): dispatch-only send/recv entry points that
never read a device value back, and a chunked exchange kernel with a
DEDICATED send/recv semaphore pair per chunk landing each chunk in its
own output slice — all recvs awaited before the first send wait, every
send drained before the kernel returns, collective id from the
registry."""

import jax
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from hpc_patterns_tpu.ops.tiling import collective_id


def _remote(src, dst, send, recv, dev):
    return pltpu.make_async_remote_copy(
        src_ref=src, dst_ref=dst, send_sem=send, recv_sem=recv,
        device_id=dev, device_id_type=pltpu.DeviceIdType.LOGICAL)


def send_migration(bundle, dst_device):
    """Dispatch-only: the payload arrays are re-homed by an ASYNC
    transfer — no readback, nothing on the host path but metadata."""
    return [jax.device_put(page, dst_device) for page in bundle]


def recv_migration(bundle, device):
    """Install-side acceptance: device METADATA checks only — the
    landing check must not synchronize the decode replica's queue."""
    for page in bundle:
        if device not in page.devices():
            raise RuntimeError("payload not resident on installer")
    return bundle


def chunked_exchange_dedicated_slots(x, n_pages, page_chunk, axis):
    """The paired exchange: chunk c's DMA reads its own input slice,
    lands in its own output slice, and signals its OWN send/recv
    semaphore pair — no slot is ever reused across families, and the
    recv-then-send drain order means no transfer outlives scratch."""
    chunks = -(-n_pages // page_chunk)

    def kernel(x_ref, o_ref, send_sem, recv_sem):
        me = lax.axis_index(axis)
        dst = lax.rem(me + 1, 2)
        dmas = []
        for c in range(chunks):
            lo = c * page_chunk
            span = min(page_chunk, n_pages - lo)
            d = _remote(x_ref.at[pl.ds(lo, span)],
                        o_ref.at[pl.ds(lo, span)],
                        send_sem.at[c], recv_sem.at[c], dst)
            d.start()
            dmas.append(d)
        for d in dmas:
            d.wait_recv()
        for d in dmas:
            d.wait_send()

    return pl.pallas_call(
        kernel,
        out_shape=x,
        in_specs=[pl.BlockSpec(memory_space=pltpu.VMEM)],
        out_specs=pl.BlockSpec(memory_space=pltpu.VMEM),
        scratch_shapes=[pltpu.SemaphoreType.DMA((chunks,)),
                        pltpu.SemaphoreType.DMA((chunks,))],
        compiler_params=pltpu.CompilerParams(
            has_side_effects=True,
            collective_id=collective_id("comm.fused.migration")),
    )(x)
