"""Known-clean: every chaos site claim, recorded injection kind,
default-site mapping, and spec-string kind prefix spells a name the
KINDS/SITES declarations carry. Zero findings expected."""

KINDS = ("straggler", "drop", "stall")
SITES = ("collective", "host_transfer")

_DEFAULT_SITE = {"straggler": "collective", "drop": "host_transfer"}


def soak(chaos, i):
    if chaos.maybe_inject("collective", i):
        chaos.record_injection("collective", i, "straggler")
        return True
    return False


def configure_soak(chaos):
    chaos.configure("stall:at=3,delay_ms=5;drop:at=7,frac=0.1")
