"""Device commands: the units of work whose overlap is measured.

The reference's three command kinds (sycl_con.cpp:84-99):

- ``C``   — compute kernel (``Q.parallel_for`` of ``busy_wait``)
- ``M2D`` — host→device copy (``Q.copy(host, dev)``)
- ``D2M`` — device→host copy (``Q.copy(dev, host)``)

Each command here has MPI-queue-like async semantics: :meth:`submit`
enqueues the work and returns immediately (JAX async dispatch ≙ an
out-of-order queue submit), :meth:`block` waits for completion (≙
``Q.wait()``). The ``submit`` paths carry the ``@dispatch_critical``
marker: jaxlint (hpc_patterns_tpu.analysis) audits them for host
readbacks, so "submit never blocks" is a checked invariant, not a
comment. A command owns its buffers, like each reference command
owning its USM allocation (sycl_con.cpp:64-73), so independent commands
share no data dependencies and the runtime is free to overlap them.

Transfers use the TPU-native path when the backend exposes memory kinds
(a jitted identity with ``pinned_host``/``device`` output sharding — an
XLA transfer op on the DMA engine) and fall back to
``device_put`` / ``copy_to_host_async`` elsewhere, so the same suite runs
on the CPU test mesh.
"""

from __future__ import annotations

import numpy as np

import jax
import jax.numpy as jnp

from hpc_patterns_tpu.analysis import dispatch_critical
from hpc_patterns_tpu.concurrency import kernels

# the probe/sharding/transfer helpers live in memory/kinds.py since
# round 11 (the residency manager needs the same answers); the old
# private names stay as delegating aliases so every command keeps its
# call sites and the memoized probe is shared process-wide
from hpc_patterns_tpu.memory.kinds import (
    kind_sharding as _kind_sharding,
    memory_kind_transfers_work as _memory_kind_transfers_work,
    move_to_kind as _move_to_kind,
)

_fresh_copy = jax.jit(lambda x: x + 0)  # shared across D2M instances


class Command:
    """Base: one unit of asynchronously-submittable device work."""

    name = "?"

    def submit(self) -> None:
        raise NotImplementedError

    def block(self) -> None:
        raise NotImplementedError

    def run_blocking(self) -> None:
        self.submit()
        self.block()

    @property
    def nbytes(self) -> int:
        return 0


class ComputeCommand(Command):
    """``C``: the busy-wait FMA chain on a device buffer
    (sycl_con.cpp:92-95). ``tripcount`` is mutable so the autotuner can
    re-balance a built command (C12)."""

    name = "C"

    def __init__(self, n_elements: int = 8 * 128, tripcount: int = 1000, device=None):
        self.device = device if device is not None else jax.devices()[0]
        self.x = kernels.compute_buffer(n_elements, self.device)
        self.tripcount = int(tripcount)
        self._pending = None

    @dispatch_critical
    def submit(self) -> None:
        self._pending = kernels.busy_wait(self.x, self.tripcount)

    def block(self) -> None:
        if self._pending is not None:
            jax.block_until_ready(self._pending)

    @property
    def nbytes(self) -> int:
        return int(self.x.size) * 4


class CopyM2DCommand(Command):
    """``M2D``: host memory → device HBM (sycl_con.cpp:96-99 with a host
    source; ``omp target update to``)."""

    name = "M2D"

    def __init__(self, n_elements: int, device=None, dtype=jnp.float32):
        self.device = device if device is not None else jax.devices()[0]
        self.n_elements = int(n_elements)
        self._pending = None
        if _memory_kind_transfers_work(self.device):
            # TPU path: source lives in pinned host memory; the transfer
            # is a jitted XLA op targeting the device memory kind.
            src = jax.device_put(
                jnp.zeros((self.n_elements,), dtype),
                _kind_sharding(self.device, "pinned_host"),
            )
            self._src = jax.block_until_ready(src)
            self._move = _move_to_kind(self.device, "device")
            self._submit = lambda: self._move(self._src)
        else:
            self._host = np.zeros((self.n_elements,), dtype)
            self._submit = lambda: jax.device_put(self._host, self.device)

    @dispatch_critical
    def submit(self) -> None:
        self._pending = self._submit()

    def block(self) -> None:
        if self._pending is not None:
            jax.block_until_ready(self._pending)

    @property
    def nbytes(self) -> int:
        return self.n_elements * 4


class CopyD2MCommand(Command):
    """``D2M``: device HBM → host memory (sycl_con.cpp:96-99 with a host
    destination; ``omp target update from``)."""

    name = "D2M"

    def __init__(self, n_elements: int, device=None, dtype=jnp.float32):
        self.device = device if device is not None else jax.devices()[0]
        self.n_elements = int(n_elements)
        self._pending = None
        self._dev = jax.block_until_ready(
            jax.device_put(jnp.zeros((self.n_elements,), dtype), self.device)
        )
        if _memory_kind_transfers_work(self.device):
            self._move = _move_to_kind(self.device, "pinned_host")
            self._mode = "memory_kind"
        else:
            # Fallback: produce a *fresh* device array each submit (a
            # cached jax.Array host copy would make the 2nd repetition a
            # no-op), then start its host transfer.
            self._fresh = _fresh_copy
            self._mode = "host_async"

    @dispatch_critical
    def submit(self) -> None:
        if self._mode == "memory_kind":
            self._pending = self._move(self._dev)
        else:
            y = self._fresh(self._dev)
            y.copy_to_host_async()
            self._pending = y

    def block(self) -> None:
        if self._pending is None:
            return
        if self._mode == "memory_kind":
            jax.block_until_ready(self._pending)
        else:
            np.asarray(self._pending)

    @property
    def nbytes(self) -> int:
        return self.n_elements * 4


_KINDS = {
    "C": ComputeCommand,
    "M2D": CopyM2DCommand,
    "D2M": CopyD2MCommand,
}


def make_command(
    kind: str,
    *,
    device=None,
    copy_elements: int = 1 << 20,
    compute_elements: int = 8 * 128,
    tripcount: int = 1000,
) -> Command:
    """Build a command from its reference CLI name (the positional command
    list of sycl_con.cpp:184-232)."""
    kind = kind.upper()
    if kind == "C":
        return ComputeCommand(compute_elements, tripcount, device)
    if kind in ("M2D", "D2M"):
        return _KINDS[kind](copy_elements, device)
    raise ValueError(f"unknown command {kind!r}; expected one of {sorted(_KINDS)}")
